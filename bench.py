#!/usr/bin/env python
"""Headline benchmark: Llama-2-architecture causal-LM pretraining throughput,
tokens/sec/chip, full train step (fwd + bwd + AdamW) under jit.

Baseline (BASELINE.json north star): Llama-2-7B pretrain > 2500 tokens/sec/chip
on TPU v5p. The local chip is whatever the driver provides (v5e today, ~16 GB
HBM), so the model is scaled to the largest Llama-proportioned config that
trains on one chip; the metric name carries the parameter count.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 2500.0

# Persisted Pallas block-size autotune cache: a short accelerator-tunnel
# window must not be burned re-sweeping block sizes, so sweep results are
# written next to the bench and committed (kernels/autotune.py loads it).
AUTOTUNE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "autotune_cache.json")


def _retry_loop(retries: int, wait: float) -> None:
    """Re-run the bench in a child process until the backend comes up.

    Retrying inside one process is unsafe: a hung backend-init thread holds
    jax's backend lock forever, so the parent re-execs itself (child runs
    with BENCH_NO_RETRY=1). Only backend-init failures are retried — a real
    bench error propagates immediately. The attempt/backoff trail is folded
    into the final JSON record as ``backend_down_attempts``, so BENCH_r*.json
    distinguishes "backend never came up" from "first attempt flaked"
    without stderr archaeology."""
    import subprocess

    env = dict(os.environ, BENCH_NO_RETRY="1")
    trail = []
    for attempt in range(retries + 1):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True,
        )
        out = proc.stdout.strip()
        tail = out.rsplit("\n", 1)[-1] if out else ""
        parsed = True
        try:
            rec = json.loads(tail)
        except ValueError:
            parsed = False
            rec = {"error": f"no JSON line (rc={proc.returncode})"}
        err = str(rec.get("error", ""))
        backend_down = proc.returncode != 0 and bool(rec.get("backend_down"))
        trail.append(
            {
                "attempt": attempt + 1,
                "rc": proc.returncode,
                "backend_down": backend_down,
                "error": err[:200],
                "wait_s": wait if backend_down and attempt < retries else 0.0,
            }
        )
        if not backend_down or attempt == retries:
            if parsed and isinstance(rec, dict):
                rec["backend_down_attempts"] = trail
                head = out.rsplit("\n", 1)[0] if "\n" in out else ""
                if head:
                    print(head, flush=True)
                print(json.dumps(rec), flush=True)
            elif out:
                print(out, flush=True)
            else:
                _fail_json(err or f"bench child produced no output (rc={proc.returncode})")
            sys.exit(proc.returncode)
        print(
            f"bench: backend down (attempt {attempt + 1}/{retries + 1}), "
            f"retrying in {wait:.0f}s: {err[:200]}",
            file=sys.stderr, flush=True,
        )
        time.sleep(wait)


def _fail_json(error: str, backend_down: bool = False) -> None:
    """One parseable failure line on stdout — the driver records stdout
    verbatim, so every exit path must leave a JSON record. ``backend_down``
    tags backend-init failures explicitly so the retry wrapper never has to
    guess from message text.

    ``status`` is the machine-readable trichotomy every record carries:
    ``"measured"`` (a real number), ``"error"`` (the bench itself failed),
    ``"infra_down"`` (the backend never came up — the number is NOT a
    measured zero and must be excluded from vs_baseline/trajectory math,
    hence ``vs_baseline: null`` here)."""
    status = "infra_down" if backend_down else "error"
    print(
        json.dumps(
            {
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": None if backend_down else 0.0,
                "status": status,
                "error": error[:500],
                "backend_down": backend_down,
            }
        ),
        flush=True,
    )


def _count_params(model) -> int:
    return int(sum(int(np.prod(p.shape)) for p in model.parameters()))


def _preflight_pallas(platform: str, cfg, seq: int, batch: int) -> None:
    """Kill-switch: statically verify each gated Pallas kernel lowers for the
    target platform at the EXACT shapes the bench will compile, BEFORE it is
    baked into the jitted train step (a Mosaic lowering error inside jit is
    uncatchable there and would cost the whole bench run — BENCH_r02 died
    exactly this way). A failing kernel flips only its own FLAGS_use_pallas_*
    off; the XLA fallback path covers it."""
    import paddle_tpu as paddle

    if platform != "tpu":
        return
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention_pallas
    from paddle_tpu.kernels.fused import fused_rms_norm_pallas, fused_rope_pallas

    hd = cfg.hidden_size // cfg.num_attention_heads

    def check(name: str, flag: str, fn, *args) -> None:
        try:
            jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
            print(f"bench: pallas preflight ok: {name}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001
            print(
                f"bench: pallas preflight FAILED ({name}), disabling {flag}: {exc!r}"[:2000],
                file=sys.stderr,
            )
            paddle.set_flags({flag: False})

    q = jnp.zeros((1, seq, cfg.num_attention_heads, hd), jnp.bfloat16)
    kv = jnp.zeros((1, seq, cfg.num_key_value_heads, hd), jnp.bfloat16)
    check(
        "flash_attention",
        "FLAGS_use_pallas_attention",
        # grad wrt q AND k/v: the backward runs as two pallas_calls (dq, dkv)
        # and an unused dkv cotangent would let DCE prune the second kernel
        # out before Mosaic lowering ever checked it
        lambda q, k, v: jax.grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=True)
            .astype(jnp.float32)
            .sum(),
            argnums=(0, 1, 2),
        )(q, k, v),
        q, kv, kv,
    )
    from paddle_tpu.kernels.paged_attention import paged_flash_decode

    bs_, mbs_, nb_ = 16, 8, 64
    pq = jnp.zeros((2, cfg.num_attention_heads, hd), jnp.bfloat16)
    pkc = jnp.zeros((nb_, cfg.num_key_value_heads, bs_, hd), jnp.bfloat16)
    ptab = jnp.zeros((2, mbs_), jnp.int32)
    plen = jnp.ones((2,), jnp.int32)
    check(
        "paged_flash_decode",
        "FLAGS_use_pallas_paged_attention",
        lambda q_, kc_, vc_, t_, l_: paged_flash_decode(q_, kc_, vc_, t_, l_),
        pq, pkc, pkc, ptab, plen,
    )
    x = jnp.zeros((2, seq, cfg.hidden_size), jnp.bfloat16)
    w = jnp.zeros((cfg.hidden_size,), jnp.bfloat16)
    rope_x = jnp.zeros((1, seq, cfg.num_attention_heads, hd), jnp.bfloat16)
    cs = jnp.zeros((1, seq, 1, hd), jnp.float32)
    # rope has a custom VJP (Pallas bwd kernel): preflight both fwd and bwd
    # lowering so the train step never hits an uncatchable Mosaic error.
    check(
        "fused_rms_norm+rope",
        "FLAGS_use_pallas_fused",
        lambda x, w, rx, c, s: (
            jax.grad(lambda x: fused_rms_norm_pallas(x, w, 1e-6).astype(jnp.float32).sum())(x),
            jax.grad(
                lambda rx: fused_rope_pallas(rx, c, s).astype(jnp.float32).sum()
            )(rx),
        ),
        x, w, rope_x, cs, cs,
    )
    from paddle_tpu.kernels.fused_loss import fused_linear_cross_entropy

    # loss head: fwd (online-logsumexp kernel) AND bwd (dX + dW kernels) at
    # the exact [B*S, H] x [H, V] shape the train step bakes in
    rows = batch * seq
    lx = jnp.zeros((rows, cfg.hidden_size), jnp.bfloat16)
    lw = jnp.zeros((cfg.hidden_size, cfg.vocab_size), jnp.bfloat16)
    ll = jnp.zeros((rows,), jnp.int32)
    check(
        "fused_linear_cross_entropy",
        "FLAGS_use_fused_loss",
        lambda lx, lw: jax.grad(
            lambda lx, lw: fused_linear_cross_entropy(lx, lw, ll), argnums=(0, 1)
        )(lx, lw),
        lx, lw,
    )


def _resolve_backend() -> str:
    """Initialize the jax backend with two defenses: (a) the lab site-hook
    overrides the ``JAX_PLATFORMS`` env var, so an explicit ``cpu`` request is
    re-applied through ``jax.config`` (the call that actually sticks); (b) a
    hung accelerator tunnel blocks backend init forever — a watchdog turns
    that into a diagnostic JSON line instead of a silent lost round."""
    import os
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    result: dict = {}

    def probe() -> None:
        try:
            result["platform"] = jax.default_backend()
            result["n"] = len(jax.devices())
        except Exception as exc:  # noqa: BLE001
            result["error"] = f"{type(exc).__name__}: {exc}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("BENCH_BACKEND_TIMEOUT", "180")))
    if "platform" not in result:
        _fail_json(
            result.get(
                "error",
                "jax backend initialization timed out (accelerator tunnel down?)",
            ),
            backend_down=True,
        )
        sys.stderr.flush()
        os._exit(1)  # the hung probe thread would block a normal exit
    print(f"bench: platform={result['platform']} devices={result['n']}", file=sys.stderr)
    return result["platform"]


def _assert_grad_coverage(paddle, model, ids, labels) -> None:
    """Honesty gate (VERDICT r3): one fwd+bwd step, then assert every
    trainable parameter received a non-None, nonzero grad. The r3 bench
    measured a step whose weight grads were silently DCE'd (recompute
    regression) — this gate makes that class of failure impossible to
    benchmark. One jitted probe returning the grads explicitly (jit
    state-capture does not persist ``.grad``; eager per-op dispatch would
    cost minutes of per-op compiles through the TPU tunnel)."""

    @paddle.jit.to_static
    def probe(model, ids, labels):
        loss, _ = model(ids, labels=labels)
        loss.backward()
        grads = [
            p.grad for p in model.parameters() if not p.stop_gradient
        ]  # None stays None in the output tree — visible host-side
        model.clear_gradients()
        return loss, grads

    _loss, grads = probe(model, ids, labels)
    names = [n for n, p in model.named_parameters() if not p.stop_gradient]
    missing = [n for n, g in zip(names, grads) if g is None]
    assert not missing, (
        f"grad-coverage: {len(missing)} trainable params got NO grad "
        f"(training is fake): {missing[:5]}"
    )
    zero = [n for n, g in zip(names, grads) if float(g.abs().sum()) == 0.0]
    assert not zero, f"grad-coverage: zero grads on {zero[:5]}"
    print(f"bench: grad-coverage ok ({len(names)} trainable params)", file=sys.stderr)


# secondaries whose measured path dispatches kernels from paddle_tpu/kernels/
# (directly or through the serving engine's decode step) — each of their
# records carries the PG preflight verdict so a hardware run never burns its
# rare TPU window on a kernel the analyzer already knows cannot lower
_KERNEL_BEARING_METRICS = {
    "int8_decode_matmul_ms",
    "paged_decode_step_ms",
    "engine_decode_tokens_per_sec",
    "fused_decode_layer_dispatches_per_layer",
    "tp_decode_tokens_per_sec",
    "shared_prefix_ttft_speedup",
    "kv_tier_multi_turn_ttft",
    "spec_decode_tokens_per_sec",
    "engine_fault_recovery_tokens_per_sec",
    "serving_goodput_tokens_per_sec",
    "cluster_goodput_tokens_per_sec",
    "quantized_kv_decode_tokens_per_sec",
}


def _kernel_geometry_clean() -> bool:
    """PG (Pallas kernel geometry) preflight over the kernels package: rank
    discipline, in-bounds proofs, VMEM budgets, scalar-prefetch, fallback
    lockstep. In-process ``--select PG`` equivalent; an analyzer crash counts
    as NOT clean (never vacuously green)."""
    try:
        from paddle_tpu.analysis import analyze_paths

        pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)), "paddle_tpu", "kernels")
        vs = analyze_paths([pkg], select=["PG"])
        n = sum(1 for v in vs if not v.suppressed)
        if n:
            print(f"bench: PG geometry preflight: {n} finding(s)", file=sys.stderr)
        return n == 0
    except Exception as exc:  # noqa: BLE001 - preflight must never kill the bench
        print(f"bench: PG geometry preflight failed: {exc!r}", file=sys.stderr)
        return False


def main() -> None:
    # backend watchdog must run before `import paddle_tpu` — the framework
    # import itself touches the backend, which hangs if the tunnel is down
    platform = _resolve_backend()

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig
    if platform == "tpu":
        # ~0.5B params: Llama proportions scaled to fit one v5e chip (16G)
        # with fp32 master weights + AdamW moments; per-layer recompute keeps
        # activations flat so batch*seq can use the full MXU.
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=1536,
            intermediate_size=4096,
            num_hidden_layers=14,
            num_attention_heads=12,
            num_key_value_heads=12,
            max_position_embeddings=2048,
            recompute=True,
        )
        batch, seq, steps, warmup = 8, 2048, 10, 2
    else:  # CPU smoke mode so the script is runnable anywhere
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 128, 3, 1

    # pin the fused loss head explicitly (and restore on exit) so the headline
    # metric never depends on a flag value left behind by another process
    # stage — same discipline as _bench_engine_decode's attention-path pin.
    # Pinned BEFORE preflight: a failing Mosaic lowering flips it back off.
    _prior_fused_loss = paddle.get_flags(["FLAGS_use_fused_loss"])
    paddle.set_flags({"FLAGS_use_fused_loss": True})
    try:
        _main_timed(platform, paddle, cfg, batch, seq, steps, warmup)
    finally:
        paddle.set_flags(_prior_fused_loss)


def _main_timed(platform, paddle, cfg, batch, seq, steps, warmup) -> None:
    from paddle_tpu.models.llama import LlamaForCausalLM

    _preflight_pallas(platform, cfg, seq, batch)
    # record what actually ran: preflight may have flipped the pin back off
    fused_loss = bool(paddle.get_flags(["FLAGS_use_fused_loss"])["FLAGS_use_fused_loss"])
    if platform == "tpu":
        # benchmark-driven Pallas block-size selection; the A/B timing lines
        # land on stderr (autotune: flash_attention ... -> (bq, bk)).
        # The flags live in kernels.autotune, which kernel modules import
        # only lazily — register them before set_flags can see them.
        import paddle_tpu.kernels.autotune  # noqa: F401

        paddle.set_flags(
            {
                "FLAGS_kernel_autotune_verbose": True,
                "FLAGS_use_kernel_autotune": True,
                # committed cache file: re-runs (and retries) skip the sweep
                "FLAGS_kernel_autotune_cache": AUTOTUNE_CACHE,
            }
        )
    paddle.seed(0)
    model = LlamaForCausalLM(cfg).to(dtype="bfloat16")
    n_params = _count_params(model)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=True
    )

    @paddle.jit.to_static
    def train_step(model, opt, ids, labels):
        loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )

    # honesty gate #1: every trainable param gets a real grad (small eager step)
    probe_ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (1, min(seq, 256))).astype(np.int32)
    )
    _assert_grad_coverage(paddle, model, probe_ids, probe_ids)

    first_loss = None
    for i in range(warmup):
        l = float(train_step(model, opt, ids, labels))  # sync: compile + settle
        if i == 0:
            first_loss = l

    t0 = time.perf_counter()
    last = None
    for _ in range(steps):
        last = train_step(model, opt, ids, labels)
    loss_val = float(last)  # device sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    assert np.isfinite(loss_val), f"non-finite loss {loss_val}"
    # honesty gate #2: the optimizer must actually be learning — same batch
    # every step, so loss strictly decreases over the measured window unless
    # the step is fake.
    assert loss_val < first_loss, (
        f"loss did not decrease over {warmup + steps} same-batch steps "
        f"({first_loss} -> {loss_val}): the measured step is not training"
    )
    print(
        f"bench: loss {first_loss:.4f} -> {loss_val:.4f} over {warmup + steps} steps",
        file=sys.stderr,
    )

    # v5e peak 197 bf16 TFLOP/s; 6*N*T FLOPs/token (fwd+bwd, weight FLOPs)
    mfu = 6.0 * n_params * tokens_per_sec / 197e12 if platform == "tpu" else 0.0

    secondary = [
        _bench_ernie(paddle, platform),
        _bench_sd_unet(paddle, platform),
        _bench_resnet_pipeline(paddle, platform),
        _bench_int8_decode(paddle, platform),
        _bench_quantized_kv_decode(paddle, platform),
        _bench_paged_decode(paddle, platform),
        _bench_engine_decode(paddle, platform),
        _bench_fused_decode_layer(paddle, platform),
        _bench_tp_decode(paddle, platform),
        _bench_shared_prefix_ttft(paddle, platform),
        _bench_kv_tier_multi_turn(paddle, platform),
        _bench_spec_decode(paddle, platform),
        _bench_engine_fault_recovery(paddle, platform),
        _bench_serving_goodput(paddle, platform),
        _bench_cluster_goodput(paddle, platform),
        _bench_traced_request_breakdown(paddle, platform),
    ]
    # explicit machine-readable status on EVERY record: a secondary that
    # returned an "error" field (or skipped itself, e.g. tp under 2
    # devices) did not measure anything — trajectory tooling must never
    # average its value as a real zero
    geometry_clean = _kernel_geometry_clean()
    for rec in secondary:
        rec.setdefault(
            "status",
            "error" if "error" in rec
            else "skipped" if "skipped" in rec
            else "measured",
        )
        if rec.get("metric") in _KERNEL_BEARING_METRICS:
            rec["geometry_clean"] = geometry_clean
    print(
        json.dumps(
            {
                "metric": f"llama_{n_params / 1e9:.2f}B_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC_PER_CHIP, 4),
                "status": "measured",
                "mfu": round(mfu, 4),
                "fused_loss": fused_loss,
                "secondary": secondary,
            }
        )
    )


def _bench_ernie(paddle, platform: str) -> dict:
    """Secondary metric (BASELINE.md config #2): ERNIE-3.0-base finetune
    step time, AMP O2 (bf16 params, fp32 master weights in AdamW)."""
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForSequenceClassification

    try:
        if platform == "tpu":
            cfg = ErnieConfig.ernie3_base()
            batch, seq, steps, warmup = 32, 128, 10, 2
        else:
            cfg = ErnieConfig.tiny()
            batch, seq, steps, warmup = 2, 16, 2, 1

        paddle.seed(0)
        model = ErnieForSequenceClassification(cfg, num_classes=2)
        opt = paddle.optimizer.AdamW(learning_rate=2e-5, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")

        @paddle.jit.to_static
        def step(model, opt, ids, labels):
            with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
                logits = model(ids)
                loss = paddle.nn.functional.cross_entropy(logits, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
        labels = paddle.to_tensor(rng.integers(0, 2, (batch,)).astype(np.int64))
        for _ in range(warmup):
            float(step(model, opt, ids, labels))
        t0 = time.perf_counter()
        last = None
        for _ in range(steps):
            last = step(model, opt, ids, labels)
        lv = float(last)
        dt = time.perf_counter() - t0
        assert np.isfinite(lv), f"non-finite ernie loss {lv}"
        return {
            "metric": "ernie3_base_finetune_step_time_ms",
            "value": round(dt / steps * 1000.0, 2),
            "unit": "ms/step",
            "batch": batch,
            "seq": seq,
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "ernie3_base_finetune_step_time_ms", "error": f"{exc!r}"[:300]}


def _bench_sd_unet(paddle, platform: str) -> dict:
    """Tertiary metric (BASELINE.md config #5): Stable-Diffusion v1.5 UNet
    inference latency through the Predictor (bf16 serving, resident weights)."""
    from paddle_tpu import inference
    from paddle_tpu.models.sd_unet import UNet2DConditionModel, UNetConfig
    from paddle_tpu.static import InputSpec

    try:
        if platform == "tpu":
            cfg = UNetConfig.sd15()
            batch, hw, ctx_len, steps, warmup = 2, 64, 77, 10, 2
        else:
            cfg = UNetConfig.tiny()
            batch, hw, ctx_len, steps, warmup = 1, 16, 8, 2, 1

        paddle.seed(0)
        model = UNet2DConditionModel(cfg)
        model.eval()
        config = inference.Config.from_layer(
            model,
            [
                InputSpec([batch, cfg.in_channels, hw, hw], "float32", name="sample"),
                InputSpec([batch], "int32", name="timestep"),
                InputSpec([batch, ctx_len, cfg.cross_attention_dim], "float32", name="context"),
            ],
        )
        if platform == "tpu":
            config.enable_mixed_precision(inference.PrecisionType.Bfloat16)
        config.enable_memory_optim(False)  # keep inputs reusable across timed runs
        predictor = inference.create_predictor(config)
        rng = np.random.default_rng(2)
        feeds = [
            rng.normal(size=(batch, cfg.in_channels, hw, hw)).astype(np.float32),
            np.full((batch,), 10, np.int32),
            rng.normal(size=(batch, ctx_len, cfg.cross_attention_dim)).astype(np.float32),
        ]
        for _ in range(warmup):
            predictor.run(feeds)
        t0 = time.perf_counter()
        for _ in range(steps):
            outs = predictor.run(feeds)
        dt = time.perf_counter() - t0
        assert np.isfinite(np.asarray(outs[0], np.float32)).all()
        return {
            "metric": "sd15_unet_inference_images_per_sec",
            "value": round(batch * steps / dt, 2),
            "unit": "images/s",
            "batch": batch,
            "latent": hw,
        }
    except Exception as exc:  # noqa: BLE001
        return {"metric": "sd15_unet_inference_images_per_sec", "error": f"{exc!r}"[:300]}


def _bench_int8_decode(paddle, platform: str) -> dict:
    """int8 vs bf16 at the decode-dominant shape (VERDICT r5 #4): a GEMV-like
    [tokens, in] x [in, out] MLP projection is HBM-bandwidth-bound at decode,
    so int8 weights (half the bytes) should approach 2x. Measures bf16
    matmul vs weight-only int8 vs true-int8 (llm.int8) through jit."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu.quantization as q

    try:
        if platform == "tpu":
            tokens, d_in, d_out, iters, warm = 8, 4096, 11008, 50, 5
        else:
            tokens, d_in, d_out, iters, warm = 2, 128, 256, 3, 1
        rng = np.random.default_rng(4)
        w = paddle.to_tensor(rng.normal(size=(d_in, d_out)).astype(np.float32) / np.sqrt(d_in))
        x = paddle.to_tensor(rng.normal(size=(tokens, d_in)).astype(np.float32))
        wb = w.astype("bfloat16")
        xb = x.astype("bfloat16")
        qw, sc = q.weight_quantize(w)

        bf16_fn = jax.jit(lambda a, ww: a @ ww)
        wol_fn = jax.jit(lambda a, qq, ss: q.weight_only_linear(
            paddle.to_tensor(a), paddle.to_tensor(qq), weight_scale=paddle.to_tensor(ss)
        )._data)
        i8_fn = jax.jit(lambda a, qq, ss: q.llm_int8_linear(
            paddle.to_tensor(a), paddle.to_tensor(qq), weight_scale=paddle.to_tensor(ss)
        )._data)

        def timed(fn, *args):
            for _ in range(warm):
                fn(*args).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            out.block_until_ready()
            return (time.perf_counter() - t0) / iters * 1e3

        t_bf16 = timed(bf16_fn, xb._data, wb._data)
        t_wol = timed(wol_fn, xb._data, qw._data, sc._data)
        t_i8 = timed(i8_fn, xb._data, qw._data, sc._data)
        return {
            "metric": "int8_decode_matmul_ms",
            "bf16_ms": round(t_bf16, 4),
            "weight_only_int8_ms": round(t_wol, 4),
            "llm_int8_ms": round(t_i8, 4),
            "weight_only_speedup_vs_bf16": round(t_bf16 / t_wol, 3),
            "shape": [tokens, d_in, d_out],
        }
    except Exception as exc:  # noqa: BLE001
        return {"metric": "int8_decode_matmul_ms", "error": f"{exc!r}"[:300]}


def _bench_quantized_kv_decode(paddle, platform: str) -> dict:
    """Quantized serving (FLAGS_kv_cache_dtype=int8 + weight-only int8):
    decode throughput and EFFECTIVE KV bytes/token against the bf16 engine,
    with the measured quality delta riding the record — greedy token-match
    rate through the full paged plane and max logit error of the quantized
    projections (inference.quality, the same harness the tier-1 tolerance
    gate asserts on). A quantized config that is fast but wrong shows up
    HERE, not in an incident."""
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.inference.quality import quality_delta
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, max_new = 8, 16, 128, 16, 48
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, max_new = 2, 4, 16, 4, 8

        def build():
            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            if platform == "tpu":
                m = m.to(dtype="bfloat16")
            m.eval()
            return m

        ekw = dict(max_slots=slots, block_size=bs, prompt_bucket=bucket)
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(
                0, cfg.vocab_size, (int(rng.integers(bucket // 2, bucket + 1)),)
            ).astype(np.int32)
            for _ in range(n_req)
        ]
        quality = quality_delta(build, prompts, max_new, ekw)

        def timed(quant: bool) -> tuple:
            eng = ContinuousBatchingEngine(
                build(),
                kv_cache_dtype="int8" if quant else "bf16",
                weight_only_int8=quant,
                **ekw,
            )
            for p in prompts:
                eng.add_request(p, max_new_tokens=max_new)
            t0 = time.perf_counter()
            out = eng.run()
            dt = time.perf_counter() - t0
            toks = sum(len(r.generated) for r in out.values())
            return toks / dt, eng.pool_stats(), eng.stats["step_traces"]

        tps_bf16, _, traces_bf16 = timed(False)
        tps_q, qstats, traces_q = timed(True)
        return {
            "metric": "quantized_kv_decode_tokens_per_sec",
            "value": round(tps_q, 2),
            "unit": "tokens/s",
            "kv_cache_dtype": qstats["kv_cache_dtype"],
            "weight_only_int8": True,
            "bf16_tokens_per_sec": round(tps_bf16, 2),
            "speedup_vs_bf16": round(tps_q / tps_bf16, 3),
            "kv_bytes_per_token_bf16": quality["kv_bytes_per_token_bf16"],
            "kv_bytes_per_token_quant": quality["kv_bytes_per_token_quant"],
            "kv_bytes_reduction": round(quality["kv_bytes_reduction"], 3),
            # honesty: quantization is data + placements, never shapes —
            # each configuration compiles exactly one step signature
            "one_compile_per_engine": bool(traces_bf16 == 1 and traces_q == 1),
            "quality": {
                "token_match_rate": round(quality["token_match_rate"], 4),
                "tokens_compared": quality["tokens_compared"],
                "max_logit_error": round(
                    float(quality.get("max_logit_error", 0.0)), 5
                ),
            },
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "quantized_kv_decode_tokens_per_sec", "error": f"{exc!r}"[:300]}


def _bench_paged_decode(paddle, platform: str) -> dict:
    """Paged-cache decode step: Pallas block-table flash-decode vs the XLA
    dense-gather path (VERDICT r5 #6 A/B). Serving shape: the whole paged
    decode step (append + attend) jitted, per-step latency."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.incubate.nn.functional.block_attention import (
        block_multihead_attention,
    )

    try:
        if platform == "tpu":
            b, hq, hkv, d, bs, mbs, nb, iters, warm = 16, 32, 32, 128, 16, 64, 1024, 30, 5
        else:
            b, hq, hkv, d, bs, mbs, nb, iters, warm = 2, 4, 4, 64, 16, 4, 16, 2, 1
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.bfloat16)
        kv = jnp.asarray(rng.normal(size=(b, 1, hkv, d)), jnp.bfloat16)
        kc = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), jnp.bfloat16)
        vc = jnp.asarray(rng.normal(size=(nb, hkv, bs, d)), jnp.bfloat16)
        tables = jnp.asarray(
            rng.permutation(nb)[: b * mbs].reshape(b, mbs), jnp.int32
        )
        lens = jnp.asarray(rng.integers(bs, mbs * bs - 1, (b,)), jnp.int32)
        step = jax.jit(block_multihead_attention)

        def timed(flag: bool) -> float:
            paddle.set_flags({"FLAGS_use_pallas_paged_attention": flag})
            jax.clear_caches()  # the flag is baked at trace time
            for _ in range(warm):
                out, _, _ = step(q, kv, kv, kc, vc, tables, lens)
            out.block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                out, _, _ = step(q, kv, kv, kc, vc, tables, lens)
            out.block_until_ready()
            return (time.perf_counter() - t0) / iters * 1e3

        t_xla = timed(False)
        t_pallas = timed(True) if platform == "tpu" else None
        rec = {
            "metric": "paged_decode_step_ms",
            "xla_gather_ms": round(t_xla, 4),
            "batch": b, "heads": hq, "ctx": int(mbs * bs),
        }
        if t_pallas is not None:
            rec["pallas_flash_decode_ms"] = round(t_pallas, 4)
            rec["pallas_speedup_vs_gather"] = round(t_xla / t_pallas, 3)
        return rec
    except Exception as exc:  # noqa: BLE001
        return {"metric": "paged_decode_step_ms", "error": f"{exc!r}"[:300]}


def _bench_engine_decode(paddle, platform: str) -> dict:
    """Continuous-batching decode throughput: a mixed-length request stream
    through the one-signature engine (``inference.ContinuousBatchingEngine``)
    — generated tokens/sec with slots refilled as sequences finish. The
    compiled-signature count rides along as an honesty check: > 1 means the
    engine retraced mid-serve and the number is measuring compiles. Runs with
    FLAGS_enable_metrics on, so the record carries the observability snapshot
    (TTFT/decode-latency percentiles, pool-utilization high-water, and the
    recompile watchdog's per-function compile counts)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    # pin the attention path explicitly (and restore it on the way out) —
    # _bench_paged_decode toggles this flag while timing, and the value it
    # happens to leave behind would otherwise decide which kernel this
    # metric measures
    flag_name = "FLAGS_use_pallas_paged_attention"
    prior_flags = paddle.get_flags([flag_name, "FLAGS_enable_metrics"])
    use_pallas = platform == "tpu"
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, max_new = 8, 16, 128, 24, 64
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, max_new = 2, 4, 16, 4, 6

        paddle.set_flags({flag_name: use_pallas, "FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()  # compile ledger counts THIS engine only
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        engine = ContinuousBatchingEngine(
            model, max_slots=slots, block_size=bs, prompt_bucket=bucket
        )
        rng = np.random.default_rng(6)

        def submit(n: int) -> None:
            for _ in range(n):
                plen = int(rng.integers(max(bucket // 4, 1), bucket + 1))
                engine.add_request(
                    rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32),
                    max_new_tokens=int(rng.integers(max_new // 2, max_new + 1)),
                )

        submit(2)  # warmup: compiles the unified step signature
        engine.run()
        # keep the watchdog ledger (the warmup compile IS the signature;
        # any compile past them is the retrace the honesty check exists for)
        # but zero the latency/pool metrics so percentiles cover only the
        # timed window
        obs.GLOBAL_METRICS.reset()
        submit(n_req)
        t0 = time.perf_counter()
        out = engine.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.generated) for r in out.values())

        wd = {
            fn: rec["count"]
            for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
            if fn.startswith("ContinuousBatchingEngine.")
        }
        ttft = obs.GLOBAL_METRICS.get("engine_ttft_seconds")
        step_h = obs.GLOBAL_METRICS.get("engine_decode_step_seconds")

        def pct(h) -> dict:
            return {
                "p50": round(h.quantile(0.5) * 1e3, 3),
                "p95": round(h.quantile(0.95) * 1e3, 3),
                "p99": round(h.quantile(0.99) * 1e3, 3),
                "count": h.count(),
            }

        return {
            "metric": "engine_decode_tokens_per_sec",
            "value": round(toks / dt, 2),
            "unit": "tokens/s",
            "requests": n_req,
            "generated_tokens": toks,
            "max_slots": slots,
            "tp_degree": engine.tp_degree,
            "attention_path": "pallas" if use_pallas else "xla_gather",
            # the watchdog's numbers, not the engine's ad-hoc counter
            "compiled_signatures": sum(wd.values()),
            "metrics": {
                "ttft_ms": pct(ttft),
                "decode_step_ms": pct(step_h),
                "kv_pool_utilization_peak": round(
                    obs.GLOBAL_METRICS.get("engine_kv_pool_utilization").high_water(), 4
                ),
                "compiles_by_fn": wd,
            },
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "engine_decode_tokens_per_sec", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior_flags)


def _bench_fused_decode_layer(paddle, platform: str) -> dict:
    """Decode-step megakernel (``FLAGS_use_fused_decode_layer``): per-layer
    dispatch count fused vs unfused from the trace-time probe (the python of
    the jitted step runs once per compile, so each armed site counts once
    per signature), byte-identity of the two token streams (the PR's
    correctness acceptance — a mismatch is recorded as an error, never as a
    throughput number), and the comm/compute story both ways: the analytic
    all-reduce share of one tp decode layer (``comm_share_analytic`` —
    row-parallel collective bytes vs MXU time at peak) NEXT TO the devprof
    measurement (``comm_share_measured`` from a profiled tp=2 fused engine,
    skipped cleanly on 1 device; ``host_bubble_fraction`` from the fused
    run's sampled steps)."""
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.kernels.fused import arm_dispatch_probe, disarm_dispatch_probe
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    flag = "FLAGS_use_fused_decode_layer"
    prior = paddle.get_flags([flag, "FLAGS_devprof_sample_rate"])
    metric = "fused_decode_layer_dispatches_per_layer"
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, max_new = 8, 16, 128, 16, 48
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, max_new = 2, 4, 16, 4, 6
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(0, cfg.vocab_size, (int(rng.integers(max(bucket // 4, 1), bucket + 1)),)).astype(np.int32)
            for _ in range(n_req)
        ]
        budgets = [int(rng.integers(max_new // 2, max_new + 1)) for _ in range(n_req)]

        def run(fused: bool):
            # every step device-profiled: the fused record carries a
            # MEASURED host-bubble fraction next to the analytic comm share
            paddle.set_flags({flag: fused, "FLAGS_devprof_sample_rate": 1.0})
            eng = ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, prompt_bucket=bucket
            )
            rids = [
                eng.add_request(p, max_new_tokens=t)
                for p, t in zip(prompts, budgets)
            ]
            arm_dispatch_probe()
            try:
                t0 = time.perf_counter()
                out = eng.run()
                dt = time.perf_counter() - t0
            finally:
                sites = disarm_dispatch_probe()
            toks = [out[r].tokens().tolist() for r in rids]
            ntoks = sum(len(out[r].generated) for r in rids)
            return (
                sites, toks, ntoks / dt, eng.stats["step_traces"],
                eng.devprof_stats(),
            )

        sites_f, toks_f, tps_f, traces_f, devprof_f = run(True)
        sites_u, toks_u, tps_u, traces_u, _devprof_u = run(False)

        # measured comm share: a devprof-profiled tp=2 fused engine over a
        # small slice of the same stream (skipped cleanly on 1 device —
        # there is no collective to measure). Under GSPMD the all-reduces
        # are compiler-inserted, so comm_source reports how the share was
        # attributed (wrapper timing vs cost-model prior).
        import jax as _jax

        ndev = len(_jax.devices())
        if ndev >= 2 and cfg.num_key_value_heads % 2 == 0:
            paddle.set_flags({flag: True, "FLAGS_devprof_sample_rate": 1.0})
            eng_tp = ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, prompt_bucket=bucket,
                tp=2,
            )
            for p, t in zip(prompts[:2], budgets[:2]):
                eng_tp.add_request(p, max_new_tokens=t)
            eng_tp.run()
            dp = eng_tp.devprof_stats()
            comm_share_measured = {
                "value": dp.get("comm_share_measured", 0.0),
                "comm_sources": dp.get("comm_sources", {}),
                "sampled_steps": dp.get("sampled_steps", 0),
                "tp_degree": 2,
                "status": "measured",
            }
        else:
            comm_share_measured = {
                "status": "skipped",
                "reason": f"needs >= 2 devices with shardable kv heads, "
                          f"have {ndev} device(s)",
            }
        if toks_f != toks_u:
            return {
                "metric": metric,
                "error": "fused/unfused token streams diverge — fusion is broken",
            }

        n_layers = cfg.num_hidden_layers
        step_f = ("fused:embed_norm", "fused:rope_gather")
        step_u = ("unfused:embed", "unfused:final_norm")
        per_layer_f = sum(v for k, v in sites_f.items() if k not in step_f) / n_layers
        per_layer_u = sum(v for k, v in sites_u.items() if k not in step_u) / n_layers

        # analytic tp all-reduce share of one decode layer per token:
        # row-parallel o_proj + down_proj each all-reduce [1, H] activations
        # over ICI while the column/row matmuls run on the MXU
        itemsize = 2 if platform == "tpu" else 4
        h, inter = cfg.hidden_size, cfg.intermediate_size
        ar_bytes = 2 * h * itemsize
        mm_flops = 2 * (4 * h * h + 3 * h * inter)
        t_ar = ar_bytes / 45e9  # v5e ICI ~45 GB/s per link
        t_mm = mm_flops / (197e12 if platform == "tpu" else 1e12)
        return {
            "metric": metric,
            "value": round(per_layer_f, 2),
            "unit": "dispatch sites/layer/step",
            "unfused_dispatches_per_layer": round(per_layer_u, 2),
            "dispatch_sites": {"fused": sites_f, "unfused": sites_u},
            "tokens_per_sec": {
                "fused": round(tps_f, 2), "unfused": round(tps_u, 2)
            },
            "byte_identical_fused_on_off": True,
            "compiled_signatures": {"fused": traces_f, "unfused": traces_u},
            # labeled analytic so it can never be confused with the devprof
            # MEASUREMENT next to it
            "comm_share_analytic": {
                "value": round(t_ar / (t_ar + t_mm), 4),
                "method": "analytic_estimate",
                "model": "2*H*itemsize bytes over ICI vs layer matmul FLOPs at peak",
            },
            "comm_share_measured": comm_share_measured,
            "host_bubble_fraction": (
                {
                    "value": devprof_f.get("mean_host_bubble_fraction", 0.0),
                    "sampled_steps": devprof_f.get("sampled_steps", 0),
                    "status": "measured",
                }
                if devprof_f.get("sampled_steps")
                else {"status": "skipped", "reason": "no sampled steps"}
            ),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": metric, "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_tp_decode(paddle, platform: str) -> dict:
    """Tensor-parallel decode throughput (guarded): the same mixed-length
    request stream through a single-chip engine and a ``tp``-sharded engine
    over the device mesh (``distributed/tp.py`` — head-parallel attention +
    per-device KV pool partition, Megatron MLP splits, vocab-sharded
    lm-head). Skips cleanly with fewer than 2 devices. Records per-chip and
    aggregate decode tokens/s, the all-reduce time share BOTH ways —
    ``comm_share_analytic`` (from scaling efficiency: ``1 - t1/(tp*t_tp)``,
    the gap between the observed sharded step and perfect linear scaling)
    next to devprof's ``comm_share_measured`` (per-sampled-step attribution,
    with its ``comm_source`` provenance) and ``host_bubble_fraction`` — the
    byte-identity of the sharded outputs, and the 1-compile-per-engine
    honesty field."""
    import jax as _jax

    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    metric = "tp_decode_tokens_per_sec"
    ndev = len(_jax.devices())
    if ndev < 2:
        return {"metric": metric, "skipped": f"needs >= 2 devices, have {ndev}"}
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, max_new = 8, 16, 128, 24, 64
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, max_new = 2, 4, 16, 4, 6
        # largest power-of-two shard count the KV heads and mesh support
        tp = 1
        while (
            tp * 2 <= min(8, ndev)
            and cfg.num_key_value_heads % (tp * 2) == 0
        ):
            tp *= 2
        if tp < 2:
            return {
                "metric": metric,
                "skipped": f"kv heads {cfg.num_key_value_heads} not shardable "
                           f"over {ndev} devices",
            }
        obs.GLOBAL_WATCHDOG.reset()
        prior_dp = paddle.get_flags(["FLAGS_devprof_sample_rate"])
        paddle.set_flags({"FLAGS_devprof_sample_rate": 1.0})

        def build(tp_degree: int):
            paddle.seed(0)
            model = LlamaForCausalLM(cfg)
            if platform == "tpu":
                model = model.to(dtype="bfloat16")
            model.eval()
            return ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, prompt_bucket=bucket,
                tp=tp_degree,
            )

        def run(engine) -> tuple:
            rng = np.random.default_rng(6)

            def submit(n: int) -> list:
                rids = []
                for _ in range(n):
                    plen = int(rng.integers(max(bucket // 4, 1), bucket + 1))
                    rids.append(engine.add_request(
                        rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32),
                        max_new_tokens=int(rng.integers(max_new // 2, max_new + 1)),
                    ))
                return rids
            submit(2)
            engine.run()  # warmup: compiles the one step signature
            rids = submit(n_req)
            t0 = time.perf_counter()
            out = engine.run()
            dt = time.perf_counter() - t0
            toks = sum(len(r.generated) for r in out.values())
            streams = [out[r].tokens().tolist() for r in rids]
            return (
                toks / dt, streams, engine.stats["step_traces"],
                engine.devprof_stats(),
            )

        try:
            tput1, streams1, compiles1, devprof1 = run(build(1))
            tput_tp, streams_tp, compiles_tp, devprof_tp = run(build(tp))
        finally:
            paddle.set_flags(prior_dp)
        # the watchdog ledger cross-checks the per-engine counters: exactly
        # one recorded step compile per engine, and none from anywhere else
        wd_steps = sum(
            rec["count"]
            for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
            if fn.startswith("ContinuousBatchingEngine.")
        )
        speedup = tput_tp / tput1 if tput1 else 0.0
        # comm share estimate: the shortfall vs perfect linear scaling of
        # the (compute-bound) sharded step — t1/t_tp == tput_tp/tput1, so
        # 1 - t1/(tp*t_tp) == 1 - tput_tp/(tp*tput1); 0 at perfect scaling
        share = max(0.0, min(1.0, 1.0 - tput_tp / (tp * tput1))) if tput1 else 0.0
        return {
            "metric": metric,
            "value": round(tput_tp, 2),
            "unit": "tokens/s",
            "tp_degree": tp,
            "per_chip_tokens_per_sec": round(tput_tp / tp, 2),
            "tp1_tokens_per_sec": round(tput1, 2),
            "speedup_vs_tp1": round(speedup, 4),
            # labeled analytic vs measured so the two can never be confused
            # downstream: the estimate infers comm from scaling shortfall,
            # the measurement attributes each sampled step's device segment
            "comm_share_analytic": {
                "value": round(share, 4),
                "method": "analytic_estimate",
                "model": "1 - tput_tp/(tp*tput1) scaling shortfall",
            },
            "comm_share_measured": (
                {
                    "value": devprof_tp.get("comm_share_measured", 0.0),
                    "comm_sources": devprof_tp.get("comm_sources", {}),
                    "sampled_steps": devprof_tp.get("sampled_steps", 0),
                    "status": "measured",
                }
                if devprof_tp.get("sampled_steps")
                else {"status": "skipped", "reason": "no sampled steps"}
            ),
            "host_bubble_fraction": {
                "tp1": devprof1.get("mean_host_bubble_fraction"),
                "tp": devprof_tp.get("mean_host_bubble_fraction"),
                "status": "measured",
            },
            "byte_identical_vs_tp1": streams_tp == streams1,
            # honesty: each engine compiled its unified step exactly once,
            # and the watchdog ledger agrees (catches stray compiles too)
            "compiles_tp1_engine": compiles1,
            "compiles_tp_engine": compiles_tp,
            "watchdog_step_compiles": wd_steps,
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": metric, "error": f"{exc!r}"[:300]}


def _bench_shared_prefix_ttft(paddle, platform: str) -> dict:
    """Prefix-cache acceptance bench (guarded): N requests share a long
    system prompt. Cold phase computes it once; the warm phase must MAP it
    (content-hash block dedup) instead of recomputing — warm TTFT below cold
    TTFT, hit rate > 0, and the prefill token-compute counter showing the
    shared prefix computed exactly once across all N requests. The 1-compile
    watchdog count rides along as the chunked-prefill honesty check."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    prior = paddle.get_flags(
        ["FLAGS_enable_metrics", "FLAGS_enable_prefix_cache"]
    )
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_warm, shared_len, tail, max_new = (
                8, 16, 256, 12, 192, 16, 16
            )
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_warm, shared_len, tail, max_new = (
                2, 4, 32, 4, 20, 3, 4
            )
        paddle.set_flags(
            {"FLAGS_enable_metrics": True, "FLAGS_enable_prefix_cache": True}
        )
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        engine = ContinuousBatchingEngine(
            model, max_slots=slots, block_size=bs, prompt_bucket=bucket
        )
        rng = np.random.default_rng(7)
        system_prompt = rng.integers(0, cfg.vocab_size, (shared_len,)).astype(np.int32)

        def submit_one():
            user = rng.integers(0, cfg.vocab_size, (tail,)).astype(np.int32)
            return engine.add_request(
                np.concatenate([system_prompt, user]), max_new_tokens=max_new
            )

        def ttfts(out):
            return sorted(
                r.admit_time - r.arrival_time for r in out.values()
            )

        # cold: ONE request computes the shared prefix (plus the engine's
        # one compile — excluded from timing by a throwaway warmup first)
        engine.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=2,
        )
        engine.run()
        computed_before = engine.stats["prompt_tokens_computed"]
        submit_one()
        cold_out = engine.run()
        cold_ttft = ttfts(cold_out)
        cold_prefix_computed = (
            engine.stats["prompt_tokens_computed"] - computed_before
        )

        # warm: N requests repeat the system prompt with distinct tails
        computed_before = engine.stats["prompt_tokens_computed"]
        for _ in range(n_warm):
            submit_one()
        warm_out = engine.run()
        warm_ttft = ttfts(warm_out)
        warm_computed = engine.stats["prompt_tokens_computed"] - computed_before

        cache = engine.prefix_cache_stats()
        wd = {
            fn: rec["count"]
            for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
            if fn.startswith("ContinuousBatchingEngine.")
        }
        # the shared prefix's full blocks were computed exactly once (by the
        # cold request); warm requests computed only tails + ragged ends
        shared_full = (shared_len // bs) * bs
        per_warm_computed = warm_computed / n_warm

        def pct(sorted_vals, q):
            if not sorted_vals:
                return 0.0
            i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
            return sorted_vals[i]

        return {
            "metric": "shared_prefix_ttft_speedup",
            "value": round(
                pct(cold_ttft, 0.5) / max(pct(warm_ttft, 0.5), 1e-9), 3
            ),
            "unit": "x (cold TTFT p50 / warm TTFT p50)",
            "cold_ttft_ms": {"p50": round(pct(cold_ttft, 0.5) * 1e3, 3),
                             "p99": round(pct(cold_ttft, 0.99) * 1e3, 3)},
            "warm_ttft_ms": {"p50": round(pct(warm_ttft, 0.5) * 1e3, 3),
                             "p99": round(pct(warm_ttft, 0.99) * 1e3, 3)},
            "shared_prefix_tokens": int(shared_len),
            "warm_requests": n_warm,
            "hit_rate": round(cache["hit_rate"], 4),
            "tokens_reused": cache["tokens_reused"],
            "bytes_saved": cache["bytes_saved"],
            "cow_forks": cache["cow_forks"],
            "prefix_computed_once": bool(
                cold_prefix_computed >= shared_full
                and per_warm_computed <= (shared_len - shared_full) + tail + bs
            ),
            "prompt_tokens_computed_per_warm_request": round(per_warm_computed, 2),
            # honesty check: chunked prefill + cache hits through ONE program
            "compiled_signatures": sum(wd.values()),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "shared_prefix_ttft_speedup", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_kv_tier_multi_turn(paddle, platform: str) -> dict:
    """Hierarchical-KV acceptance bench (guarded): warm TTFT of a seeded
    multi-turn conversation trace against a DELIBERATELY small device pool
    — the regime the host tier exists for: the conversations' chains do not
    fit HBM, so between turns they get evicted, and turn k+1 either
    recomputes its whole history (tier off) or prefetches it H2D from host
    RAM (tier on). Reports warm-TTFT p50/p99, prefix hit rate and
    spill/prefetch/drop counters across a host-cache-size sweep
    (``FLAGS_kv_host_tier_bytes`` 0 = off, then small, then ample), plus
    the 1-compile honesty check: spill and prefetch are pure data movement
    outside the traced step, so the recompile watchdog must still report
    exactly ONE compile per engine at every sweep point."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    prior = paddle.get_flags(["FLAGS_enable_metrics"])
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
            )
            slots, bs, num_blocks, bucket, max_len = 4, 16, 96, 1024, 1536
            n_convs, n_turns, turn_tail, max_new = 6, 4, 48, 32
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, num_blocks, bucket, max_len = 2, 4, 12, 40, 56
            n_convs, n_turns, turn_tail, max_new = 3, 3, 4, 3
        paddle.set_flags({"FLAGS_enable_metrics": False})
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        bytes_per_block = (
            2 * cfg.num_hidden_layers * cfg.num_key_value_heads
            * (cfg.hidden_size // cfg.num_attention_heads) * bs
            * (2 if platform == "tpu" else 4)
        )
        # the whole trace's chain working set, in blocks — "small" holds
        # about a third of it, "ample" all of it
        worst_blocks = n_convs * (
            (n_turns * (turn_tail + max_new)) // bs + 1
        )
        sweep_budgets = [0, (worst_blocks // 3) * bytes_per_block,
                         worst_blocks * bytes_per_block]

        def drive(tier_bytes):
            obs.GLOBAL_WATCHDOG.reset()
            engine = ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, num_blocks=num_blocks,
                prompt_bucket=bucket, max_model_len=max_len,
                kv_host_tier_bytes=tier_bytes,
            )
            rng = np.random.default_rng(11)
            streams = {}
            warm_ttfts = []
            # warmup: the engine's one compile, off the clock
            engine.add_request(
                rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                max_new_tokens=2,
            )
            engine.run()
            # the seeded trace: conversations interleave round-robin, so a
            # conversation's chains face the other conversations' pool
            # pressure between its own turns
            for turn in range(n_turns):
                for conv in range(n_convs):
                    tail = rng.integers(
                        0, cfg.vocab_size, (turn_tail,)
                    ).astype(np.int32)
                    prev = streams.get(conv)
                    prompt = (
                        tail if prev is None
                        else np.concatenate([prev, tail])
                    )
                    cap = min(bucket, max_len - max_new - bs)
                    if prompt.size > cap:
                        prompt = prompt[-cap:]
                    rid = engine.add_request(prompt, max_new_tokens=max_new)
                    out = engine.run()
                    streams[conv] = out[rid].tokens()
                    if turn > 0:
                        warm_ttfts.append(
                            out[rid].admit_time - out[rid].arrival_time
                        )
            warm_ttfts.sort()
            cache = engine.prefix_cache_stats()
            tier = engine.kv_tier_stats()
            wd = {
                fn: rec["count"]
                for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
                if fn.startswith("ContinuousBatchingEngine.")
            }

            def pct(q):
                if not warm_ttfts:
                    return 0.0
                i = min(len(warm_ttfts) - 1, int(q * len(warm_ttfts)))
                return warm_ttfts[i]

            lookups = cache["hits"] + cache["misses"]
            return {
                "kv_host_tier_bytes": int(tier_bytes),
                "warm_ttft_ms": {"p50": round(pct(0.5) * 1e3, 3),
                                 "p99": round(pct(0.99) * 1e3, 3)},
                "hit_rate": round(cache["hit_rate"], 4),
                "host_hit_rate": round(
                    cache["host_hits"] / lookups if lookups else 0.0, 4
                ),
                "tokens_reused": cache["tokens_reused"],
                "spilled_blocks": tier.get("spilled_blocks", 0),
                "prefetched_blocks": tier.get("prefetched_blocks", 0),
                "dropped_blocks": tier.get("dropped_blocks", 0),
                "host_bytes_peak": tier.get("host_bytes", 0),
                "compiled_signatures": sum(wd.values()),
            }

        sweep = [drive(b) for b in sweep_budgets]
        off_p50 = sweep[0]["warm_ttft_ms"]["p50"]
        best_on = min(pt["warm_ttft_ms"]["p50"] for pt in sweep[1:])
        return {
            "metric": "kv_tier_multi_turn_ttft",
            "value": round(off_p50 / max(best_on, 1e-9), 3),
            "unit": "x (tier-off warm TTFT p50 / best tier-on p50)",
            "device_pool_blocks": num_blocks,
            "trace": {"conversations": n_convs, "turns": n_turns,
                      "turn_tail_tokens": turn_tail, "max_new": max_new},
            "sweep": sweep,
            # honesty: data movement added zero compiled signatures anywhere
            "compiled_signatures_per_engine": max(
                pt["compiled_signatures"] for pt in sweep
            ),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "kv_tier_multi_turn_ttft", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_spec_decode(paddle, platform: str) -> dict:
    """Speculative-decoding acceptance bench (guarded): decode tokens/s with
    n-gram self-speculation off vs on over a REPETITIVE continuation
    workload — the regime speculation exists for (templated text, code,
    multi-turn chats, the cyclic tails greedy decode settles into).

    Construction (fully seeded, honest): phase A generates continuations
    for a pool of seeded candidate prompts, scores each result by OFFLINE
    drafter self-acceptance (would the prompt-lookup drafter have predicted
    each of the last ``span`` tokens from the tokens before it?), and keeps
    the candidates whose continuations are genuinely self-predictable —
    exactly the requests speculation targets. Phase B times the SAME
    continuation requests (prompt = candidate + its phase-A continuation,
    so decoding resumes inside the repetitive regime) through two engines,
    speculation off then on, and reports the tokens/s ratio alongside the
    honesty checks: greedy outputs byte-identical between the two runs, and
    the recompile watchdog showing exactly ONE compile per engine — drafts
    and rewinds are data on the one ``[max_slots, prefill_chunk]``
    signature, never a new program."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine, NGramDrafter
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    prior = paddle.get_flags(
        ["FLAGS_enable_metrics", "FLAGS_spec_decode_tokens",
         "FLAGS_spec_decode_ngram"]
    )
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
            )
            slots, bs, chunk, spec_k = 8, 16, 16, 8
            n_cand, probe_new, max_new, keep = 16, 160, 128, 8
            bucket, model_len = 512, 1024
        else:  # tiny CPU smoke: the same machinery with a small budget
            cfg = LlamaConfig.tiny()
            slots, bs, chunk, spec_k = 2, 4, 8, 7
            n_cand, probe_new, max_new, keep = 16, 120, 96, 6
            bucket, model_len = 192, 512
        paddle.set_flags({
            "FLAGS_enable_metrics": True,
            "FLAGS_spec_decode_tokens": spec_k,
            "FLAGS_spec_decode_ngram": 3,
        })
        obs.GLOBAL_METRICS.reset()
        paddle.seed(3)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        rng = np.random.default_rng(9)
        cands = [
            rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
            for _ in range(n_cand)
        ]

        def make_engine(spec_on):
            return ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, prompt_bucket=bucket,
                prefill_chunk=chunk, max_model_len=model_len,
                spec_decode=spec_on,
            )

        drafter = NGramDrafter(3)

        def self_acceptance(tokens, span=24):
            hits = 0
            for t in range(len(tokens) - span, len(tokens)):
                prop = drafter.propose(np.asarray(tokens[:t], np.int32), 1)
                hits += prop.size == 1 and int(prop[0]) == tokens[t]
            return hits / span

        # phase A (untimed): generate candidate continuations, keep the
        # self-predictable ones — the repetitive slice of the traffic
        eng0 = make_engine(False)
        rids = [eng0.add_request(p, max_new_tokens=probe_new) for p in cands]
        out0 = eng0.run()
        scored = sorted(
            ((self_acceptance(list(out0[r].tokens())), r) for r in rids),
            reverse=True,
        )
        prompts = [out0[r].tokens() for s, r in scored if s >= 0.6][:keep]
        if len(prompts) < 2:  # never run an empty workload
            prompts = [out0[r].tokens() for _, r in scored[:2]]

        def timed(spec_on):
            obs.GLOBAL_WATCHDOG.reset()  # compile ledger counts THIS engine
            eng = make_engine(spec_on)
            eng.add_request(cands[0][:4], max_new_tokens=2)
            eng.run()  # the one compile happens outside the timed window
            rids_ = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
            t0 = time.perf_counter()
            out = eng.run()
            dt = time.perf_counter() - t0
            toks = sum(len(out[r].generated) for r in rids_)
            wd = sum(
                rec["count"]
                for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
                if fn.startswith("ContinuousBatchingEngine.")
            )
            return eng, [out[r].tokens() for r in rids_], toks / dt, wd

        eng_off, toks_off, tps_off, wd_off = timed(False)
        eng_on, toks_on, tps_on, wd_on = timed(True)
        identical = all(
            np.array_equal(a, b) for a, b in zip(toks_off, toks_on)
        )
        spec = eng_on.spec_decode_stats()
        return {
            "metric": "spec_decode_tokens_per_sec",
            "value": round(tps_on, 2),
            "unit": "tokens/s (speculation on, repetitive continuation workload)",
            "speedup_vs_off": round(tps_on / tps_off, 3) if tps_off else 0.0,
            "baseline_tokens_per_sec": round(tps_off, 2),
            "acceptance_rate": round(spec["acceptance_rate"], 4),
            "drafted_tokens": spec["drafted_tokens"],
            "accepted_tokens": spec["accepted_tokens"],
            "speculative_steps": spec["speculative_steps"],
            "steps_off": eng_off.stats["steps"],
            "steps_on": eng_on.stats["steps"],
            "requests": len(prompts),
            "max_new_tokens": max_new,
            "draft_tokens_max": spec_k,
            # honesty checks: same greedy stream, same ONE compiled program
            "greedy_identical_on_vs_off": bool(identical),
            "compiled_signatures_per_engine": {"off": wd_off, "on": wd_on},
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "spec_decode_tokens_per_sec", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_engine_fault_recovery(paddle, platform: str) -> dict:
    """Fault-injection smoke (guarded): one injected decode-step fault
    mid-workload; the engine must recover — reallocate the KV pools, replay
    every live request from host truth — and finish the whole workload
    through the SAME compiled program. Records the recovered decode
    throughput and the recovery counters, so a fault-tolerance regression
    shows up in BENCH_r*.json, not just in tier-1."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.testing import faults

    prior = paddle.get_flags(["FLAGS_enable_metrics"])
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, max_new = 4, 16, 128, 8, 32
        else:
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, max_new = 2, 4, 16, 4, 6

        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        engine = ContinuousBatchingEngine(
            model, max_slots=slots, block_size=bs, prompt_bucket=bucket
        )
        rng = np.random.default_rng(6)
        for _ in range(n_req):
            plen = int(rng.integers(max(bucket // 4, 1), bucket + 1))
            engine.add_request(
                rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32),
                max_new_tokens=int(rng.integers(max_new // 2, max_new + 1)),
            )
        # the fault lands mid-workload (a few dispatches in), after the
        # signature compiled — the recovery itself is what's timed
        plan = faults.FaultPlan.single("engine.decode", call_index=3)
        t0 = time.perf_counter()
        with faults.inject(plan):
            out = engine.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.generated) for r in out.values())
        reg = obs.GLOBAL_METRICS
        wd = {
            fn: rec["count"]
            for fn, rec in obs.GLOBAL_WATCHDOG.report().items()
            if fn.startswith("ContinuousBatchingEngine.")
        }
        assert len(out) == n_req, f"requests lost across recovery: {len(out)}/{n_req}"
        return {
            "metric": "engine_fault_recovery_tokens_per_sec",
            "value": round(toks / dt, 2),
            "unit": "tokens/s",
            "requests": n_req,
            "generated_tokens": toks,
            "faults_injected": int(reg.get("faults_injected_total").total()),
            "recoveries": int(reg.get("engine_recoveries_total").value()),
            "requests_replayed": int(reg.get("engine_requests_replayed_total").value()),
            # honesty check: recovery must REUSE the one compiled program
            "compiled_signatures": sum(wd.values()),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "engine_fault_recovery_tokens_per_sec", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_serving_goodput(paddle, platform: str) -> dict:
    """Open-loop overload bench (guarded): seeded Poisson arrivals at 2x the
    calibrated sustainable rate, a tenant/priority mix with per-class SLOs,
    through the full serving frontend (bounded intake, weighted fair
    admission, deadlines, hysteresis shedding). Reports GOODPUT — tokens of
    requests that finished inside their SLO — plus per-class SLO attainment
    and the shed/deadline accounting, with the 2-compile honesty check: an
    overload storm must be absorbed by scheduling, never by recompiling.
    Seeded arrivals make reruns comparable (the arrival schedule, class mix
    and prompt shapes all derive from the seeds below)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Priority, ServingConfig, ServingFrontend
    from paddle_tpu.serving.loadgen import (
        TrafficClass,
        measure_sustainable_rate,
        poisson_arrivals,
        run_open_loop,
    )

    prior = paddle.get_flags(["FLAGS_enable_metrics"])
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_arrivals, calib = 8, 16, 128, 96, 16
            plen, max_new, slo_s, max_queue = (16, 96), (16, 48), 8.0, 32
        else:  # tiny CPU smoke: the same machinery with a small budget
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_arrivals, calib = 2, 4, 16, 24, 6
            plen, max_new, slo_s, max_queue = (3, 8), (3, 8), 2.0, 8

        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()  # compile ledger counts THIS engine only
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        engine = ContinuousBatchingEngine(
            model, max_slots=slots, block_size=bs, prompt_bucket=bucket
        )
        frontend = ServingFrontend(engine, ServingConfig(max_queue=max_queue))
        rate = measure_sustainable_rate(
            frontend, calib, seed=7, prompt_len=plen, max_new_tokens=max_new,
            vocab_size=cfg.vocab_size,
        )
        # calibration traffic must not pollute the overload window's counters
        obs.GLOBAL_METRICS.reset()
        mix = [
            TrafficClass("chat", Priority.INTERACTIVE, 2.0, plen, max_new, slo_s),
            TrafficClass("app", Priority.STANDARD, 2.0, plen, max_new, slo_s),
            TrafficClass("batch", Priority.BEST_EFFORT, 1.0, plen, max_new, slo_s),
        ]
        arrivals = poisson_arrivals(
            2.0 * rate, n_arrivals, mix, seed=8, vocab_size=cfg.vocab_size
        )
        report = run_open_loop(frontend, arrivals, max_wall_s=120.0)
        reg = obs.GLOBAL_METRICS
        shed = reg.get("serving_shed_total")
        shed_by_reason = {
            v["labels"]["reason"]: int(v["value"]) for v in shed._snapshot_values()
        }
        return {
            "metric": "serving_goodput_tokens_per_sec",
            "value": report["goodput_tokens_per_sec"],
            "unit": "tokens/s",
            "offered_rate_rps": round(2.0 * rate, 2),
            "sustainable_rate_rps": round(rate, 2),
            "arrivals": n_arrivals,
            "slo_s": slo_s,
            "slo_attainment": {
                k: v["slo_attainment"] for k, v in report["per_class"].items()
            },
            "shed_total_by_reason": shed_by_reason,
            "deadline_misses": int(
                reg.get("serving_deadline_miss_total").total()
            ),
            "overload_level_peak": int(
                reg.get("serving_overload_level").high_water()
            ),
            # honesty check: overload must add ZERO compiles past the two
            # signatures calibration warmed up
            "compiled_signatures": report["compiled_signatures_total"],
            "compiles_during_overload": sum(
                report["compiles_during_run"].values()
            ),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "serving_goodput_tokens_per_sec", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)


def _bench_cluster_goodput(paddle, platform: str) -> dict:
    """Cluster-scale overload bench (guarded): three ``ServingFrontend``
    replicas behind the prefix-affinity router, seeded Poisson arrivals at
    2x the calibrated CLUSTER rate (per-replica sustainable rate x replica
    count), and ONE REPLICA KILLED MID-STORM through the ``replica.kill``
    fault site. Reports aggregate goodput, per-class SLO attainment,
    failover latency p99, salvage/re-dispatch accounting, and the affinity
    hit rate before vs after the kill (the survivors' rendezvous shares are
    untouched, so warmth should largely survive the membership change) —
    with the honesty checks: exactly one compiled signature per engine, and
    the storm window (kill included) adds ZERO compiles.

    The fleet observability layer rides along: a ClusterObserver drives the
    SLO burn-rate monitor from the router's probe loop, and the record
    carries the monitor's state timeline (time-in-WARN/PAGE across the
    kill) plus the 1-compile-per-engine proof that the whole observability
    layer — replica-scoped metrics, burn-rate sampling, incident snapshots —
    adds ZERO compiled signatures."""
    import tempfile as _tempfile

    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (
        Priority,
        ReplicaCluster,
        ReplicaRouter,
        RouterConfig,
        ServingConfig,
        ServingFrontend,
    )
    from paddle_tpu.serving.loadgen import (
        TrafficClass,
        measure_sustainable_rate,
        poisson_arrivals,
        run_cluster_open_loop,
    )
    from paddle_tpu.testing import faults

    prior = paddle.get_flags(["FLAGS_enable_metrics"])
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_arrivals, calib = 4, 16, 128, 96, 12
            plen, max_new, slo_s, max_queue = (16, 96), (16, 48), 8.0, 16
        else:  # tiny CPU smoke: the same machinery with a small budget
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_arrivals, calib = 2, 4, 16, 24, 6
            plen, max_new, slo_s, max_queue = (3, 8), (3, 8), 2.0, 8
        n_replicas, kill_frac = 3, 0.4

        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()  # compile ledger counts THESE engines only
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()

        # replicas share the model object (read-only at inference): identical
        # weights are what makes failover re-generation deterministic
        def factory(name):
            eng = ContinuousBatchingEngine(
                model, max_slots=slots, block_size=bs, prompt_bucket=bucket
            )
            return ServingFrontend(eng, ServingConfig(max_queue=max_queue))

        cluster = ReplicaCluster(factory, [f"r{i}" for i in range(n_replicas)])
        router = ReplicaRouter(cluster, RouterConfig())
        # fleet observability riding the probe loop: the burn-rate monitor's
        # windows are sized to the storm (the kill must register as
        # sustained within the run), the TTFT target is the workload SLO
        observer = obs.ClusterObserver(
            router,
            slo_config=obs.SLOConfig(
                ttft_p99_target_s=slo_s, goodput_target=0.9,
                shed_budget=0.1, failover_budget=0.1,
                fast_window_s=1.0, slow_window_s=4.0, min_terminals=4,
            ),
            incident_dir=_tempfile.mkdtemp(prefix="paddle_tpu_bench_incidents_"),
            incident_cooldown_s=5.0,
        )
        # per-replica capacity from ONE replica (they are identical), then
        # warm the other engines so the storm window adds no compiles
        rate = measure_sustainable_rate(
            cluster.replicas["r0"].frontend, calib, seed=7, prompt_len=plen,
            max_new_tokens=max_new, vocab_size=cfg.vocab_size,
        )
        warm_rng = np.random.default_rng(9)
        for name in list(cluster.names())[1:]:
            fe = cluster.replicas[name].frontend
            h = fe.submit(
                warm_rng.integers(0, cfg.vocab_size, (plen[0],)).astype(np.int32),
                max_new_tokens=max_new[0],
            )
            while not h.finished:
                fe.pump()
        obs.GLOBAL_METRICS.reset()  # calibration must not pollute the storm

        mix = [
            TrafficClass("chat", Priority.INTERACTIVE, 2.0, plen, max_new, slo_s),
            TrafficClass("app", Priority.STANDARD, 2.0, plen, max_new, slo_s),
            TrafficClass("batch", Priority.BEST_EFFORT, 1.0, plen, max_new, slo_s),
        ]
        offered = 2.0 * n_replicas * rate
        arrivals = poisson_arrivals(
            offered, n_arrivals, mix, seed=8, vocab_size=cfg.vocab_size
        )
        kill_at_s = arrivals[int(kill_frac * len(arrivals))].t
        state = {"killed": False, "counters_at_kill": None}

        def mid_storm(router_, now):
            if not state["killed"] and now >= kill_at_s:
                state["killed"] = True
                state["counters_at_kill"] = router_.routing_counters()
                # the kill goes through the fault SITE: the next replica
                # probe trips it, so the full death-as-routing-event path
                # (salvage, re-dispatch, failover accounting) is exercised.
                # A trigger fires at most once — no uninstall race.
                faults.install_plan(faults.FaultPlan.single("replica.kill", 0))

        report = run_cluster_open_loop(
            router, arrivals, max_wall_s=120.0, on_iteration=mid_storm
        )
        counters_end = router.routing_counters()
        before = state["counters_at_kill"] or {}
        after_delta = {k: counters_end[k] - before.get(k, 0) for k in counters_end}

        def hit_rate(c):
            tot = sum(c.values())
            return round(c.get("affinity", 0) / tot, 4) if tot else 0.0

        reg = obs.GLOBAL_METRICS
        # sum across the replica-scoped cells AND the router's unscoped
        # ones: one reason may now have one cell per replica
        shed_by_reason: dict = {}
        for v in reg.family("serving_shed_total")._snapshot_values():
            reason = v["labels"]["reason"]
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + int(v["value"])
        dead = [n for n, r in cluster.replicas.items() if r.state == "dead"]
        slo_time = observer.monitor.time_in_states()
        compiled_total = report["compiled_signatures_total"]
        return {
            "metric": "cluster_goodput_tokens_per_sec",
            "value": report["goodput_tokens_per_sec"],
            "unit": "tokens/s",
            "replicas": n_replicas,
            "offered_rate_rps": round(offered, 2),
            "sustainable_rate_per_replica_rps": round(rate, 2),
            "arrivals": n_arrivals,
            "slo_s": slo_s,
            "killed_replica": dead[0] if dead else None,
            "kill_at_s": round(kill_at_s, 3),
            "slo_attainment": {
                k: v["slo_attainment"] for k, v in report["per_class"].items()
            },
            "affinity_hit_rate": {
                "before_kill": hit_rate(before),
                "after_kill": hit_rate(after_delta),
                "overall": report["affinity_hit_rate"],
            },
            "failover_latency_p99_ms": report["failover_latency_p99_ms"],
            "failovers": report["failovers"],
            "salvaged": report["salvaged"],
            "redispatch_sheds": report["router_sheds"],
            "shed_total_by_reason": shed_by_reason,
            "replica_states": report["replica_states"],
            # the SLO monitor's view of the storm: burn-rate state timeline
            # and how long the kill held the fleet in WARN/PAGE
            "slo_monitor": {
                "final_state": observer.monitor.state_name,
                "time_in_warn_s": slo_time.get("warn", 0.0),
                "time_in_page_s": slo_time.get("page", 0.0),
                "transitions": [
                    {k: e[k] for k in ("from", "to", "signal", "burn")}
                    for e in observer.monitor.timeline
                ],
            },
            "incidents_written": len(observer.incidents),
            # honesty checks: one program per engine; a replica death is
            # absorbed by routing, never by a surviving engine recompiling —
            # and the whole fleet observability layer (scoped metrics,
            # burn-rate sampling, incident snapshots) adds ZERO signatures
            "compiled_signatures": compiled_total,
            "compiles_during_storm": sum(report["compiles_during_run"].values()),
            "one_compile_per_engine": bool(
                compiled_total == n_replicas
                and sum(report["compiles_during_run"].values()) == 0
            ),
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "cluster_goodput_tokens_per_sec", "error": f"{exc!r}"[:300]}
    finally:
        faults.install_plan(None)
        paddle.set_flags(prior)


def _bench_traced_request_breakdown(paddle, platform: str) -> dict:
    """Per-request latency attribution (guarded): run a small traced serving
    workload (FLAGS_trace_sample_rate=1, seeded) and report ONE sampled
    request's queue/prefill/decode/stream phase breakdown from its span
    tree, plus the batched-decode share attribution. The 2-compile honesty
    check confirms the tracing instrumentation added no compiled
    signatures: spans are emitted at call sites from host timestamps, never
    from inside the jitted bodies (analyzer check OB601)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingConfig, ServingFrontend

    prior = paddle.get_flags(["FLAGS_trace_sample_rate", "FLAGS_trace_seed"])
    try:
        if platform == "tpu":
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=1024,
            )
            slots, bs, bucket, n_req, plen, max_new = 8, 16, 128, 16, 64, 48
        else:  # tiny CPU smoke: the same machinery with a small budget
            cfg = LlamaConfig.tiny()
            slots, bs, bucket, n_req, plen, max_new = 2, 4, 16, 4, 6, 6

        paddle.set_flags({"FLAGS_trace_sample_rate": 1.0, "FLAGS_trace_seed": 0})
        obs.GLOBAL_TRACER.clear()
        obs.GLOBAL_WATCHDOG.reset()  # compile ledger counts THIS engine only
        paddle.seed(0)
        rng = np.random.default_rng(0)
        model = LlamaForCausalLM(cfg)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        model.eval()
        engine = ContinuousBatchingEngine(
            model, max_slots=slots, block_size=bs, prompt_bucket=bucket
        )
        frontend = ServingFrontend(engine, ServingConfig(max_queue=2 * n_req))
        handles = [
            frontend.submit(
                rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32),
                max_new_tokens=max_new,
            )
            for _ in range(n_req)
        ]
        for _ in range(100_000):
            frontend.pump()
            if all(h.finished for h in handles):
                break
        assert all(h.outcome == "ok" for h in handles), [
            h.outcome for h in handles
        ]
        # pick a mid-pack request: it queued behind others AND shared its
        # decode steps, so every phase is non-trivial
        target = handles[min(len(handles) - 1, slots)]
        spans = {
            s["name"]: s for s in obs.GLOBAL_TRACER.spans(target.trace_ctx.trace_id)
        }
        root = spans["request"]
        phases_ms = {
            name.split(".", 1)[1]: round(spans[name]["dur_us"] / 1e3, 3)
            for name in ("request.queue_wait", "request.prefill",
                         "request.decode", "request.stream_out")
        }
        compiles = obs.GLOBAL_WATCHDOG.counts()
        return {
            "metric": "traced_request_breakdown",
            "value": round(root["dur_us"] / 1e3, 3),
            "unit": "ms (one sampled request, end to end)",
            "phases_ms": phases_ms,
            "phase_sum_ms": round(sum(phases_ms.values()), 3),
            "decode_steps": spans["request.decode"]["attrs"]["decode_steps"],
            "decode_batched_share_s": spans["request.decode"]["attrs"][
                "batched_share_s"
            ],
            "requests": n_req,
            # honesty check: tracing must add ZERO compiled signatures —
            # still exactly one unified prefill/decode program
            "compiled_signatures": {
                "step": compiles.get("ContinuousBatchingEngine.step", 0),
            },
        }
    except Exception as exc:  # noqa: BLE001 - secondary must never kill primary
        return {"metric": "traced_request_breakdown", "error": f"{exc!r}"[:300]}
    finally:
        paddle.set_flags(prior)
        from paddle_tpu import observability as obs

        obs.GLOBAL_TRACER.clear()


def _bench_resnet_pipeline(paddle, platform: str) -> dict:
    """Quaternary metric (BASELINE.md config #1): ResNet classification
    throughput through the REAL input pipeline — on-disk dataset, multiprocess
    DataLoader workers (shared-memory/native-ring handoff), train step under
    jit. Synthetic images (this environment has no ImageNet), but every byte
    crosses disk -> worker process -> parent -> device."""
    import shutil
    import tempfile

    from paddle_tpu.io import DataLoader
    from paddle_tpu.vision.datasets import DatasetFolder
    from paddle_tpu.vision.models.resnet import resnet18, resnet50

    tmp = tempfile.mkdtemp(prefix="bench_resnet_")
    try:
        if platform == "tpu":
            build, batch, hw, n_imgs, classes, steps, workers = resnet50, 64, 224, 512, 8, 6, 4
        else:
            build, batch, hw, n_imgs, classes, steps, workers = resnet18, 8, 32, 32, 4, 2, 2

        rng = np.random.default_rng(3)
        per = n_imgs // classes
        for c in range(classes):
            d = f"{tmp}/class_{c}"
            os.makedirs(d, exist_ok=True)
            for i in range(per):
                np.save(
                    f"{d}/{i}.npy",
                    rng.integers(0, 255, (3, hw, hw)).astype(np.uint8),
                )

        def to_float(img):
            return img.astype(np.float32) / 255.0

        ds = DatasetFolder(tmp, transform=to_float)
        loader = DataLoader(
            ds, batch_size=batch, num_workers=workers, shuffle=True,
            drop_last=True, persistent_workers=True,
        )
        paddle.seed(0)
        model = build(num_classes=classes)
        if platform == "tpu":
            model = model.to(dtype="bfloat16")
        opt = paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, parameters=model.parameters()
        )

        @paddle.jit.to_static
        def step(model, opt, x, y):
            logits = model(x)
            # F.cross_entropy upcasts to fp32 internally (stable logsumexp)
            loss = paddle.nn.functional.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        dt_dtype = "bfloat16" if platform == "tpu" else "float32"
        # warmup: one FULL epoch (compile + settle workers). Epochs always
        # drain completely — a mid-epoch break would tear down the persistent
        # pool and let leftover results poison the timed epoch.
        last = None
        for xb, yb in loader:
            last = step(model, opt, xb.astype(dt_dtype), yb)
        float(last)
        t0 = time.perf_counter()
        n_done = 0
        while n_done < steps:  # whole timed epochs until enough steps
            for xb, yb in loader:
                last = step(model, opt, xb.astype(dt_dtype), yb)
                n_done += 1
        lv = float(last)
        dt = time.perf_counter() - t0
        assert np.isfinite(lv), f"non-finite resnet loss {lv}"
        pool = getattr(loader, "_pool", None)
        if pool is not None:
            pool.shutdown()
        return {
            "metric": "resnet_train_images_per_sec_with_input_pipeline",
            "value": round(batch * n_done / dt, 1),
            "unit": "images/s",
            "batch": batch,
            "image": hw,
            "workers": workers,
        }
    except Exception as exc:  # noqa: BLE001
        return {"metric": "resnet_train_images_per_sec_with_input_pipeline", "error": f"{exc!r}"[:300]}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import argparse

    _ap = argparse.ArgumentParser(description=__doc__)
    _ap.add_argument("--retry", type=int, default=int(os.environ.get("BENCH_RETRY", "2")),
                     help="re-run the bench this many extra times if backend init fails")
    _ap.add_argument("--retry-wait", type=float,
                     default=float(os.environ.get("BENCH_RETRY_WAIT", "60")),
                     help="seconds between backend-init retries")
    _args = _ap.parse_args()
    if _args.retry > 0 and not os.environ.get("BENCH_NO_RETRY"):
        _retry_loop(_args.retry, _args.retry_wait)
        raise SystemExit  # _retry_loop always exits; belt-and-braces
    try:
        main()
    except Exception as exc:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        _fail_json(f"{type(exc).__name__}: {exc}")
        sys.exit(1)
