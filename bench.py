#!/usr/bin/env python
"""Headline benchmark: Llama-2-architecture causal-LM pretraining throughput,
tokens/sec/chip, full train step (fwd + bwd + AdamW) under jit.

Baseline (BASELINE.json north star): Llama-2-7B pretrain > 2500 tokens/sec/chip
on TPU v5p. The local chip is whatever the driver provides (v5e today, ~16 GB
HBM), so the model is scaled to the largest Llama-proportioned config that
trains on one chip; the metric name carries the parameter count.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 2500.0


def _count_params(model) -> int:
    return int(sum(int(np.prod(p.shape)) for p in model.parameters()))


def main() -> None:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    platform = jax.default_backend()
    if platform == "tpu":
        # ~0.5B params: Llama proportions scaled to fit one v5e chip (16G)
        # with fp32 master weights + AdamW moments; per-layer recompute keeps
        # activations flat so batch*seq can use the full MXU.
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=1536,
            intermediate_size=4096,
            num_hidden_layers=14,
            num_attention_heads=12,
            num_key_value_heads=12,
            max_position_embeddings=2048,
            recompute=True,
        )
        batch, seq, steps, warmup = 8, 2048, 10, 2
    else:  # CPU smoke mode so the script is runnable anywhere
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 128, 3, 1

    paddle.seed(0)
    model = LlamaForCausalLM(cfg).to(dtype="bfloat16")
    n_params = _count_params(model)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=True
    )

    @paddle.jit.to_static
    def train_step(model, opt, ids, labels):
        loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )

    for _ in range(warmup):
        float(train_step(model, opt, ids, labels))  # sync: compile + settle

    t0 = time.perf_counter()
    last = None
    for _ in range(steps):
        last = train_step(model, opt, ids, labels)
    loss_val = float(last)  # device sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    assert np.isfinite(loss_val), f"non-finite loss {loss_val}"
    print(
        json.dumps(
            {
                "metric": f"llama_{n_params / 1e9:.2f}B_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
