#!/usr/bin/env python
"""Headline benchmark: Llama-2-architecture causal-LM pretraining throughput,
tokens/sec/chip, full train step (fwd + bwd + AdamW) under jit.

Baseline (BASELINE.json north star): Llama-2-7B pretrain > 2500 tokens/sec/chip
on TPU v5p. The local chip is whatever the driver provides (v5e today, ~16 GB
HBM), so the model is scaled to the largest Llama-proportioned config that
trains on one chip; the metric name carries the parameter count.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 2500.0


def _fail_json(error: str) -> None:
    """One parseable failure line on stdout — the driver records stdout
    verbatim, so every exit path must leave a JSON record."""
    print(
        json.dumps(
            {
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": 0.0,
                "error": error[:500],
            }
        ),
        flush=True,
    )


def _count_params(model) -> int:
    return int(sum(int(np.prod(p.shape)) for p in model.parameters()))


def _preflight_pallas(platform: str, cfg, seq: int) -> None:
    """Kill-switch: statically verify each gated Pallas kernel lowers for the
    target platform at the EXACT shapes the bench will compile, BEFORE it is
    baked into the jitted train step (a Mosaic lowering error inside jit is
    uncatchable there and would cost the whole bench run — BENCH_r02 died
    exactly this way). A failing kernel flips only its own FLAGS_use_pallas_*
    off; the XLA fallback path covers it."""
    import paddle_tpu as paddle

    if platform != "tpu":
        return
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention_pallas
    from paddle_tpu.kernels.fused import fused_rms_norm_pallas, fused_rope_pallas

    hd = cfg.hidden_size // cfg.num_attention_heads

    def check(name: str, flag: str, fn, *args) -> None:
        try:
            jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
            print(f"bench: pallas preflight ok: {name}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001
            print(
                f"bench: pallas preflight FAILED ({name}), disabling {flag}: {exc!r}"[:2000],
                file=sys.stderr,
            )
            paddle.set_flags({flag: False})

    q = jnp.zeros((1, seq, cfg.num_attention_heads, hd), jnp.bfloat16)
    kv = jnp.zeros((1, seq, cfg.num_key_value_heads, hd), jnp.bfloat16)
    check(
        "flash_attention",
        "FLAGS_use_pallas_attention",
        # grad wrt q AND k/v: the backward runs as two pallas_calls (dq, dkv)
        # and an unused dkv cotangent would let DCE prune the second kernel
        # out before Mosaic lowering ever checked it
        lambda q, k, v: jax.grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=True)
            .astype(jnp.float32)
            .sum(),
            argnums=(0, 1, 2),
        )(q, k, v),
        q, kv, kv,
    )
    x = jnp.zeros((2, seq, cfg.hidden_size), jnp.bfloat16)
    w = jnp.zeros((cfg.hidden_size,), jnp.bfloat16)
    rope_x = jnp.zeros((1, seq, cfg.num_attention_heads, hd), jnp.bfloat16)
    cs = jnp.zeros((1, seq, 1, hd), jnp.float32)
    # rope has no custom VJP: its grad fails at TRACE time, which the eager
    # warn_fallback try/except already catches — only Mosaic lowering of the
    # forward is uncatchable, so that is what the preflight must cover.
    check(
        "fused_rms_norm+rope",
        "FLAGS_use_pallas_fused",
        lambda x, w, rx, c, s: (
            jax.grad(lambda x: fused_rms_norm_pallas(x, w, 1e-6).astype(jnp.float32).sum())(x),
            fused_rope_pallas(rx, c, s),
        ),
        x, w, rope_x, cs, cs,
    )


def _resolve_backend() -> str:
    """Initialize the jax backend with two defenses: (a) the lab site-hook
    overrides the ``JAX_PLATFORMS`` env var, so an explicit ``cpu`` request is
    re-applied through ``jax.config`` (the call that actually sticks); (b) a
    hung accelerator tunnel blocks backend init forever — a watchdog turns
    that into a diagnostic JSON line instead of a silent lost round."""
    import os
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    result: dict = {}

    def probe() -> None:
        try:
            result["platform"] = jax.default_backend()
            result["n"] = len(jax.devices())
        except Exception as exc:  # noqa: BLE001
            result["error"] = f"{type(exc).__name__}: {exc}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("BENCH_BACKEND_TIMEOUT", "180")))
    if "platform" not in result:
        _fail_json(
            result.get(
                "error",
                "jax backend initialization timed out (accelerator tunnel down?)",
            )
        )
        sys.stderr.flush()
        os._exit(1)  # the hung probe thread would block a normal exit
    print(f"bench: platform={result['platform']} devices={result['n']}", file=sys.stderr)
    return result["platform"]


def main() -> None:
    # backend watchdog must run before `import paddle_tpu` — the framework
    # import itself touches the backend, which hangs if the tunnel is down
    platform = _resolve_backend()

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    if platform == "tpu":
        # ~0.5B params: Llama proportions scaled to fit one v5e chip (16G)
        # with fp32 master weights + AdamW moments; per-layer recompute keeps
        # activations flat so batch*seq can use the full MXU.
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=1536,
            intermediate_size=4096,
            num_hidden_layers=14,
            num_attention_heads=12,
            num_key_value_heads=12,
            max_position_embeddings=2048,
            recompute=True,
        )
        batch, seq, steps, warmup = 8, 2048, 10, 2
    else:  # CPU smoke mode so the script is runnable anywhere
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 128, 3, 1

    _preflight_pallas(platform, cfg, seq)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg).to(dtype="bfloat16")
    n_params = _count_params(model)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=True
    )

    @paddle.jit.to_static
    def train_step(model, opt, ids, labels):
        loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )

    for _ in range(warmup):
        float(train_step(model, opt, ids, labels))  # sync: compile + settle

    t0 = time.perf_counter()
    last = None
    for _ in range(steps):
        last = train_step(model, opt, ids, labels)
    loss_val = float(last)  # device sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    assert np.isfinite(loss_val), f"non-finite loss {loss_val}"
    print(
        json.dumps(
            {
                "metric": f"llama_{n_params / 1e9:.2f}B_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        _fail_json(f"{type(exc).__name__}: {exc}")
        sys.exit(1)
