#!/bin/bash
# Background TPU tunnel probe (round 5). The axon tunnel goes down for hours;
# this loop retries backend init every ~3 min and runs the full bench the
# moment it comes up, persisting the autotune cache for the driver's own run.
# A real bench failure (backend_down=false in the JSON) stops the loop so a
# deterministic bug doesn't burn the TPU window re-running, and its record
# is preserved instead of clobbered.
cd /root/repo || exit 1
for i in $(seq 1 200); do
  if timeout 150 python -c "import jax; b=jax.default_backend(); assert b != 'cpu', b; print('UP', b, len(jax.devices()))" >> .tunnel_probe.log 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel UP on attempt $i" >> .tunnel_probe.log
    BENCH_NO_RETRY=1 timeout 4000 python bench.py > .bench_probe.json 2>> .tunnel_probe.log
    rc=$?
    echo "$(date -u +%FT%TZ) bench rc=$rc" >> .tunnel_probe.log
    if [ "$rc" -eq 0 ]; then exit 0; fi
    # stop only on an EXPLICIT non-tunnel failure; a missing/stale file means
    # the tunnel likely dropped mid-bench -- keep retrying
    if grep -q '"backend_down": false' .bench_probe.json 2>/dev/null; then
      echo "$(date -u +%FT%TZ) real bench failure (not tunnel) -- stopping probe" >> .tunnel_probe.log
      cp .bench_probe.json ".bench_probe.fail.$i.json"
      exit 2
    fi
  else
    echo "$(date -u +%FT%TZ) attempt $i: tunnel down" >> .tunnel_probe.log
  fi
  sleep 180
done
exit 1
