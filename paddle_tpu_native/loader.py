"""Loader for the native C++ runtime library (``cpp/`` → ctypes).

Builds on demand with ``make -C cpp`` when the .so is missing and a toolchain
exists; every consumer degrades gracefully to its pure-python fallback when
the library is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_REPO_ROOT, "cpp", "build", "libpaddle_tpu_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_locked(cpp_dir: str) -> bool:
    """Run make under an exclusive file lock: concurrent ranks launched
    together must not interleave compiles into the same build dir."""
    import fcntl

    os.makedirs(os.path.join(cpp_dir, "build"), exist_ok=True)
    lock_path = os.path.join(cpp_dir, "build", ".build.lock")
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(_LIB_PATH):  # another rank built it meanwhile
                return True
            subprocess.run(
                ["make", "-C", cpp_dir], check=True, capture_output=True, timeout=120
            )
            return True
    except Exception:
        return False


def load_native(build: bool = True) -> Optional[ctypes.CDLL]:
    """The native lib; with ``build=True`` compiles it on first use (under a
    cross-process lock). ``build=False`` only loads an existing .so — used by
    import-time consumers (profiler) so ``import paddle_tpu`` never blocks on
    a compile."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried and (not build or os.path.exists(_LIB_PATH)):
        return _lib
    if not os.path.exists(_LIB_PATH):
        if not build:
            return None
        _tried = True
        cpp_dir = os.path.join(_REPO_ROOT, "cpp")
        if not os.path.isdir(cpp_dir) or not _build_locked(cpp_dir):
            return None
    _tried = True
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    # tcp store
    lib.tcpstore_master_start.restype = ctypes.c_void_p
    lib.tcpstore_master_start.argtypes = [ctypes.c_int]
    lib.tcpstore_master_port.restype = ctypes.c_int
    lib.tcpstore_master_port.argtypes = [ctypes.c_void_p]
    lib.tcpstore_master_stop.argtypes = [ctypes.c_void_p]
    lib.tcpstore_connect.restype = ctypes.c_int
    lib.tcpstore_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int
    ]
    lib.tcpstore_add.restype = ctypes.c_int64
    lib.tcpstore_add.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int64]
    lib.tcpstore_wait.restype = ctypes.c_int
    lib.tcpstore_wait.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    try:
        # guarded: a prebuilt .so from before the delete op may lack the
        # symbol; TCPStore.delete degrades to a no-op in that case
        lib.tcpstore_delete.restype = ctypes.c_int
        lib.tcpstore_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    except AttributeError:
        pass
    lib.tcpstore_close.argtypes = [ctypes.c_int]
    # host tracer
    lib.het_enable.argtypes = [ctypes.c_int]
    lib.het_enabled.restype = ctypes.c_int
    lib.het_record.argtypes = [ctypes.c_char_p, ctypes.c_double, ctypes.c_double, ctypes.c_uint64]
    lib.het_drain_json.restype = ctypes.c_int
    lib.het_drain_json.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.het_count.restype = ctypes.c_int
    _lib = lib
    return _lib
