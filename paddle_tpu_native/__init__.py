"""paddle_tpu_native: stdlib-only bindings to the native C++ runtime.

This package deliberately has NO dependency on jax/numpy or on the
``paddle_tpu`` package: rendezvous (TCPStore) must work in a process whose
accelerator runtime is unhealthy or absent (reference keeps its store in
``paddle/phi/core/distributed/store/`` for the same reason — it is linked
below the device layer, ``tcp_store.h:121``).

Contents:
  - ``loader``   — ctypes loader for ``cpp/build/libpaddle_tpu_native.so``
  - ``store``    — Store / TCPStore rendezvous key-value store
  - ``shm_ring`` — shared-memory ring arena (DataLoader batch handoff)
"""

from paddle_tpu_native.loader import load_native  # noqa: F401
from paddle_tpu_native.store import Store, TCPStore  # noqa: F401
from paddle_tpu_native.shm_ring import ShmRing  # noqa: F401
