"""ShmRing: ctypes binding for the native shared-memory ring arena.

Reference analog: the C++ shared-memory transport under the reference's
multiprocess DataLoader (``mmap_allocator.cc`` + worker shared-memory tensor
conversion). One POSIX shm segment holds N fixed-size slots; producers
(forked workers) claim EMPTY slots, memcpy the payload, and commit with a
monotone ticket; the consumer (parent) drains in commit order. Per-batch
``SharedMemory`` create/unlink churn is replaced by slot reuse.

Stdlib-only (ctypes); falls back unavailable when the native lib is absent.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

from paddle_tpu_native.loader import load_native

__all__ = ["ShmRing", "available"]


def _bind():
    lib = load_native()
    if lib is None:
        return None
    try:
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.shm_ring_slot_bytes.restype = ctypes.c_uint64
        lib.shm_ring_slot_bytes.argtypes = [ctypes.c_void_p]
        lib.shm_ring_nslots.restype = ctypes.c_uint32
        lib.shm_ring_nslots.argtypes = [ctypes.c_void_p]
        lib.shm_ring_acquire_write.restype = ctypes.c_int
        lib.shm_ring_acquire_write.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.shm_ring_slot_ptr.restype = ctypes.c_void_p
        lib.shm_ring_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shm_ring_commit_write.restype = ctypes.c_int
        lib.shm_ring_commit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.shm_ring_abort_write.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shm_ring_acquire_read.restype = ctypes.c_int
        lib.shm_ring_acquire_read.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.shm_ring_release_read.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
    except AttributeError:
        return None
    return lib


_LIB = _bind()


def available() -> bool:
    return _LIB is not None


class ShmRing:
    """Fixed-slot shared-memory ring. ``create=True`` owns the segment
    (unlinked on close); workers attach by name after fork/spawn."""

    def __init__(self, name: str, nslots: int = 8, slot_bytes: int = 1 << 20,
                 create: bool = True) -> None:
        if _LIB is None:
            raise RuntimeError("native library not built (make -C cpp)")
        self._h = _LIB.shm_ring_open(
            name.encode(), int(nslots), int(slot_bytes), 1 if create else 0
        )
        if not self._h:
            raise OSError(f"shm_ring_open failed for {name!r} (create={create})")
        self.name = name
        self.nslots = int(_LIB.shm_ring_nslots(self._h))
        self.slot_bytes = int(_LIB.shm_ring_slot_bytes(self._h))

    # -- producer -----------------------------------------------------------
    def put(self, data: bytes, tag: int = 0, timeout: float = -1.0) -> bool:
        """Copy ``data`` into a free slot and publish it. False on timeout."""
        if len(data) > self.slot_bytes:
            raise ValueError(f"payload {len(data)} > slot_bytes {self.slot_bytes}")
        slot = _LIB.shm_ring_acquire_write(self._h, float(timeout))
        if slot < 0:
            return False
        try:
            ptr = _LIB.shm_ring_slot_ptr(self._h, slot)
            ctypes.memmove(ptr, data, len(data))
            rc = _LIB.shm_ring_commit_write(self._h, slot, len(data), int(tag))
            if rc != 0:
                raise OSError(f"shm_ring_commit_write rc={rc}")
            return True
        except Exception:
            _LIB.shm_ring_abort_write(self._h, slot)
            raise

    # -- consumer -----------------------------------------------------------
    def get(self, timeout: float = -1.0) -> Optional[Tuple[bytes, int]]:
        """Next payload in commit order as (bytes, tag); None on timeout."""
        size = ctypes.c_uint64()
        tag = ctypes.c_int64()
        slot = _LIB.shm_ring_acquire_read(
            self._h, float(timeout), ctypes.byref(size), ctypes.byref(tag)
        )
        if slot < 0:
            return None
        try:
            ptr = _LIB.shm_ring_slot_ptr(self._h, slot)
            data = ctypes.string_at(ptr, size.value)
        finally:
            _LIB.shm_ring_release_read(self._h, slot)
        return data, int(tag.value)

    def close(self) -> None:
        if getattr(self, "_h", None):
            _LIB.shm_ring_close(self._h)
            self._h = None

    def __enter__(self) -> "ShmRing":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
