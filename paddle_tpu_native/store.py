"""TCPStore: the rendezvous key-value store (stdlib-only — no jax/numpy).

Reference: ``paddle/phi/core/distributed/store/tcp_store.h:121`` (master +
clients over sockets). The data path is the native C++ implementation
(``cpp/tcp_store.cpp``) via ctypes; an in-process threading fallback keeps the
single-process API available when no toolchain exists. Used by
``init_parallel_env`` / launch for exchanging bootstrap blobs before any
collective backend is up.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from paddle_tpu_native.loader import load_native

__all__ = ["TCPStore", "Store"]


class Store:
    """Abstract store (reference ``store.h:24``)."""

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError

    def wait(self, key: str) -> None:
        raise NotImplementedError

    def check(self, key: str) -> bool:
        """Non-blocking existence probe. ``get``/``wait`` are RENDEZVOUS
        primitives — a missing key blocks the full store timeout waiting to
        appear — which is wrong for liveness scans (elastic membership, a
        watch loop polling per-rank keys): there, a missing key is an
        answer, not something to wait for."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        """Remove ``key``; True when it existed. The GC primitive for
        counter/generation-namespaced keys (elastic beat/fault leases,
        ``all_gather_object`` slots): without it every generation bump or
        gather call strands keys in the store forever — the unbounded-store
        failure the CM1003 analyzer rule gates on."""
        raise NotImplementedError


class _PyMaster:
    """Pure-python master fallback (same wire behavior, in-process only)."""

    def __init__(self) -> None:
        self._kv: Dict[str, bytes] = {}
        self._counters: Dict[str, int] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._kv[key] = value
            self._cond.notify_all()

    def get(self, key: str, timeout: float) -> bytes:
        with self._cond:
            ok = self._cond.wait_for(lambda: key in self._kv, timeout)
            if not ok:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
            return self._kv[key]

    def add(self, key: str, amount: int) -> int:
        with self._cond:
            v = self._counters.get(key, 0) + amount
            self._counters[key] = v
            self._kv[key] = str(v).encode()
            self._cond.notify_all()
            return v

    def check(self, key: str) -> bool:
        with self._cond:
            return key in self._kv

    def delete(self, key: str) -> bool:
        # no notify: get/wait predicates only test presence, so removal can
        # never satisfy a sleeping waiter (same contract as the native side)
        with self._cond:
            self._counters.pop(key, None)
            return self._kv.pop(key, None) is not None


class TCPStore(Store):
    """``TCPStore(host, port, is_master, world_size, timeout)`` parity."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        is_master: bool = False,
        world_size: int = 1,
        timeout: float = 300.0,
    ) -> None:
        self._lib = load_native()
        self._timeout = timeout
        self._master_handle = None
        self._fd = -1
        self._py: Optional[_PyMaster] = None
        self.host = host
        self.port = port

        if self._lib is not None:
            if is_master:
                self._master_handle = self._lib.tcpstore_master_start(port)
                if not self._master_handle:
                    raise RuntimeError(f"TCPStore master failed to bind port {port}")
                # port 0 = kernel-chosen ephemeral port; reflect the real one
                self.port = port = self._lib.tcpstore_master_port(self._master_handle)
            elif port == 0:
                raise ValueError("TCPStore client needs the master's real port (got 0)")
            self._fd = self._lib.tcpstore_connect(
                host.encode(), port, int(timeout * 1000)
            )
            if self._fd < 0:
                if self._master_handle:
                    self._lib.tcpstore_master_stop(self._master_handle)
                raise RuntimeError(f"TCPStore could not connect to {host}:{port}")
        else:
            # in-process fallback: only valid single-process (tests/dev) — a
            # private map can never rendezvous across processes
            if world_size > 1 or not is_master:
                raise RuntimeError(
                    "native TCPStore unavailable (cpp/ not built) — required "
                    "for multi-process rendezvous; run `make -C cpp`"
                )
            self._py = _PyMaster()

    # -- Store API ---------------------------------------------------------
    def set(self, key: str, value: Any) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._py is not None:
            self._py.set(key, data)
            return
        if self._lib.tcpstore_set(self._fd, key.encode(), data, len(data)) != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str) -> bytes:
        if self._py is not None:
            return self._py.get(key, self._timeout)
        import ctypes

        cap = 1 << 16
        timeout_ms = int(self._timeout * 1000)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.tcpstore_get(self._fd, key.encode(), buf, cap, timeout_ms)
            if n == -2:
                cap *= 4
                continue
            if n == -3:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out after {self._timeout}s")
            if n < 0:
                raise RuntimeError(f"TCPStore.get({key!r}) failed")
            return buf.raw[:n]

    def add(self, key: str, amount: int = 1) -> int:
        if self._py is not None:
            return self._py.add(key, amount)
        v = self._lib.tcpstore_add(self._fd, key.encode(), amount)
        if v < 0 and amount >= 0:
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return int(v)

    def check(self, key: str) -> bool:
        """Existence probe that returns promptly whether or not the key is
        there. The wire protocol has no dedicated probe, but the server's
        wait handler evaluates its predicate immediately on entry, so a
        1 ms wait IS the probe (``timeout_ms == 0`` means wait FOREVER on
        the server — never pass that here). The 1 ms bound is SERVER-side
        only — how long the server waits for an absent key to appear; the
        client then blocks on the reply read like every other op, so
        network RTT can delay the answer but never flip a present key to
        absent or desync the connection."""
        if self._py is not None:
            return self._py.check(key)
        return self._lib.tcpstore_wait(self._fd, key.encode(), 1) == 0

    def delete(self, key: str) -> bool:
        if self._py is not None:
            return self._py.delete(key)
        fn = getattr(self._lib, "tcpstore_delete", None)
        if fn is None:
            # stale prebuilt .so without the delete op: GC degrades to a
            # no-op rather than failing the caller (callers treat delete as
            # best-effort cleanup, never as a correctness dependency)
            return False
        rc = fn(self._fd, key.encode())
        if rc < 0:
            raise RuntimeError(f"TCPStore.delete({key!r}) failed")
        return bool(rc)

    def wait(self, key: str) -> None:
        if self._py is not None:
            self._py.get(key, self._timeout)
            return
        rc = self._lib.tcpstore_wait(self._fd, key.encode(), int(self._timeout * 1000))
        if rc == -3:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out after {self._timeout}s")
        if rc != 0:
            raise RuntimeError(f"TCPStore.wait({key!r}) failed")

    def __del__(self) -> None:
        try:
            if self._lib is not None and self._fd >= 0:
                self._lib.tcpstore_close(self._fd)
            if self._master_handle:
                self._lib.tcpstore_master_stop(self._master_handle)
        except Exception:
            pass
