"""Core runtime: Tensor, autograd tape, dispatch, device, dtype, RNG."""
