"""Loader for the native C++ runtime library — re-export.

The implementation lives in the stdlib-only top-level package
``paddle_tpu_native`` so that rendezvous-side consumers (launch children,
TCPStore subprocesses) can load it without importing ``paddle_tpu`` (and
therefore without touching the jax runtime at all).
"""

from paddle_tpu_native.loader import load_native  # noqa: F401

__all__ = ["load_native"]
