"""Eager autograd engine: a reverse-mode tape over ``jax.vjp``.

TPU-native counterpart of the reference's dygraph autograd
(``paddle/fluid/eager``): ``GradNode`` ≈ ``egr::GradNodeBase``
(``grad_node_info.h:197``), ``backward`` ≈ ``egr::RunBackward``
(``backward.cc:105``). Instead of per-op hand-written grad kernels, each node
captures the ``vjp`` of its forward function at dispatch time (residuals live
on device, like the reference's ``TensorWrapper`` saved tensors), and backward
is a topological sweep over the node DAG with in-degree counting — the same
queue algorithm as ``RunBackward``.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from paddle_tpu.errors import InvalidArgumentError, PreconditionNotMetError

_grad_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_grad_state, "enabled", True)


def _set_enabled(value: bool) -> None:
    _grad_state.enabled = value


class set_grad_enabled:  # noqa: N801 - context-manager API parity
    def __init__(self, mode: bool) -> None:
        self._mode = bool(mode)
        self._prev: Optional[bool] = None

    def __enter__(self) -> "set_grad_enabled":
        self._prev = is_grad_enabled()
        _set_enabled(self._mode)
        return self

    def __exit__(self, *exc: Any) -> None:
        _set_enabled(self._prev if self._prev is not None else True)

    def __call__(self, fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with set_grad_enabled(self._mode):
                return fn(*args, **kwargs)

        return wrapper


class no_grad(set_grad_enabled):  # noqa: N801
    """Disable gradient recording (``paddle.no_grad`` parity)."""

    def __init__(self, fn: Optional[Callable] = None) -> None:
        super().__init__(False)
        self._fn = fn

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self._fn is not None:
            with set_grad_enabled(False):
                return self._fn(*args, **kwargs)
        return super().__call__(*args, **kwargs)


class enable_grad(set_grad_enabled):  # noqa: N801
    def __init__(self) -> None:
        super().__init__(True)


class GradNode:
    """One recorded op on the tape.

    Holds the ``vjp`` closure produced at dispatch, the tensors it must route
    input-gradients to, and the output avals needed to materialize zero
    cotangents for outputs that received no upstream gradient (the reference
    zero-fills via ``GradTensorHolder``).
    """

    __slots__ = (
        "name",
        "vjp_fn",
        "input_tensors",
        "out_avals",
        "released",
        "__weakref__",
    )

    def __init__(
        self,
        name: str,
        vjp_fn: Callable,
        input_tensors: Sequence[Any],
        out_avals: Sequence[jax.ShapeDtypeStruct],
    ) -> None:
        self.name = name
        self.vjp_fn = vjp_fn
        self.input_tensors = list(input_tensors)
        self.out_avals = list(out_avals)
        self.released = False

    def release(self) -> None:
        """Drop residuals after backward (unless retain_graph)."""
        self.vjp_fn = None  # type: ignore[assignment]
        self.input_tensors = []
        self.released = True

    def __repr__(self) -> str:
        return f"GradNode({self.name}, n_inputs={len(self.input_tensors)})"


def _zero_cotangent(aval: jax.ShapeDtypeStruct) -> Any:
    if np.issubdtype(np.dtype(aval.dtype), np.floating) or np.issubdtype(
        np.dtype(aval.dtype), np.complexfloating
    ):
        return jax.numpy.zeros(aval.shape, aval.dtype)
    # Integer/bool outputs take symbolic-zero (float0) cotangents under jax.vjp.
    return np.zeros(aval.shape, jax.dtypes.float0)


def _coerce_cotangent(cot: Any, aval: jax.ShapeDtypeStruct) -> Any:
    """Match the cotangent to the node's recorded output aval (dtype casts can
    arise from AMP autocast boundaries)."""
    if cot is None:
        return _zero_cotangent(aval)
    if hasattr(cot, "dtype") and cot.dtype != jax.dtypes.float0 and np.dtype(cot.dtype) != np.dtype(aval.dtype):
        cot = cot.astype(aval.dtype)
    if hasattr(cot, "shape") and tuple(cot.shape) != tuple(aval.shape):
        cot = jax.numpy.broadcast_to(cot, aval.shape)
    return cot


def _accumulate(a: Any, b: Any) -> Any:
    if a is None:
        return b
    if b is None:
        return a
    return a + b


def run_backward(
    tensors: Sequence[Any],
    grad_tensors: Optional[Sequence[Any]] = None,
    retain_graph: bool = False,
) -> None:
    """Reverse sweep from ``tensors``; accumulates ``.grad`` on leaf tensors.

    Mirrors ``egr::RunBackward`` (reference ``backward.cc:105``): build the
    in-degree map over reachable nodes, seed a ready-queue with the output
    nodes, pop/run/route until empty.
    """
    from paddle_tpu.core.tensor import Tensor

    import jax.numpy as jnp

    roots: List[Tensor] = [t for t in tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    if len(grad_tensors) != len(roots):
        raise InvalidArgumentError(
            f"grad_tensors length {len(grad_tensors)} != tensors length {len(roots)}"
        )

    # node -> {output_index: cotangent}
    pending: Dict[GradNode, Dict[int, Any]] = defaultdict(dict)
    seeds: List[GradNode] = []

    for t, g in zip(roots, grad_tensors):
        if t.stop_gradient and t.grad_node is None:
            raise PreconditionNotMetError(
                "backward() called on a tensor with stop_gradient=True and no "
                "recorded graph; nothing to differentiate."
            )
        if g is None:
            if not np.issubdtype(np.dtype(t.dtype), np.floating):
                raise InvalidArgumentError(
                    f"backward() root must be floating point, got {t.dtype}"
                )
            cot = jnp.ones(t.shape, t.dtype)
        else:
            cot = g.data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t.grad_node
        if node is None:
            # Leaf root: accumulate directly.
            t._accumulate_grad(cot)
            continue
        if node.released:
            raise PreconditionNotMetError(
                "backward() through an already-freed graph; pass retain_graph=True "
                "to backward() if you need to backprop twice."
            )
        prev = pending[node].get(t.grad_output_index)
        pending[node][t.grad_output_index] = _accumulate(prev, cot)
        if node not in seeds:
            seeds.append(node)

    # --- discover reachable subgraph + consumer counts (in-degree map) -------
    dependents: Dict[GradNode, int] = defaultdict(int)
    visited = set()
    stack = list(seeds)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for inp in node.input_tensors:
            nxt = inp.grad_node
            if nxt is not None and not nxt.released:
                dependents[nxt] += 1
                if id(nxt) not in visited:
                    stack.append(nxt)

    ready = deque(n for n in seeds if dependents.get(n, 0) == 0)
    executed = set()

    def _mark_done(nxt: GradNode) -> None:
        """A consumer edge of nxt resolved; enqueue/skip when all resolved."""
        dependents[nxt] -= 1
        if dependents[nxt] == 0 and id(nxt) not in executed:
            if pending.get(nxt):
                ready.append(nxt)
            else:
                # No gradient ever reached this node: don't run its vjp, but
                # still resolve its own producers so they aren't orphaned.
                executed.add(id(nxt))
                inputs = nxt.input_tensors
                if not retain_graph:
                    nxt.release()
                for inp2 in inputs:
                    up = inp2.grad_node
                    if up is not None and not up.released:
                        _mark_done(up)

    def route(inp: Any, g: Any) -> None:
        """Deliver gradient g to input tensor inp (leaf accumulate or enqueue)."""
        is_zero = g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0)
        nxt = inp.grad_node
        if is_zero:
            if nxt is not None and not nxt.released:
                _mark_done(nxt)
            return
        g = inp._apply_backward_hooks(g)
        if nxt is None:
            if not inp.stop_gradient:
                inp._accumulate_grad(g)
            return
        prev = pending[nxt].get(inp.grad_output_index)
        pending[nxt][inp.grad_output_index] = _accumulate(prev, g)
        if inp.retain_grads_flag:
            inp._accumulate_grad(g)
        _mark_done(nxt)

    while ready:
        node = ready.popleft()
        if id(node) in executed:
            continue
        executed.add(id(node))
        cots_map = pending.pop(node, {})
        cots = tuple(
            _coerce_cotangent(cots_map.get(i), aval) for i, aval in enumerate(node.out_avals)
        )
        if len(node.out_avals) == 1:
            in_grads = node.vjp_fn(cots[0])
        else:
            in_grads = node.vjp_fn(cots)
        if len(in_grads) != len(node.input_tensors):
            raise PreconditionNotMetError(
                f"vjp of {node.name} returned {len(in_grads)} grads for "
                f"{len(node.input_tensors)} inputs"
            )
        inputs = node.input_tensors
        if not retain_graph:
            node.release()
        for inp, g in zip(inputs, in_grads):
            route(inp, g)


def grad(
    outputs: Sequence[Any],
    inputs: Sequence[Any],
    grad_outputs: Optional[Sequence[Any]] = None,
    retain_graph: bool = False,
    create_graph: bool = False,
    allow_unused: bool = False,
) -> List[Any]:
    """``paddle.grad`` parity: partial grads of outputs w.r.t. inputs.

    Reference: ``egr::Grad`` (``paddle/fluid/eager/backward.cc:450``) /
    general_grad. Implemented by running the tape backward with grad capture
    redirected into fresh buffers instead of ``.grad`` accumulation.
    """
    from paddle_tpu.core.tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True (higher-order eager grad) is not supported; use "
            "the functional API (paddle_tpu.jit / jax.grad composition) instead."
        )
    outputs = list(outputs)
    inputs = list(inputs)
    saved = [(t.grad, t.retain_grads_flag, t.stop_gradient) for t in inputs]
    try:
        for t in inputs:
            t._grad = None
            t.retain_grads_flag = True
            # Ensure leaves accept accumulation during this sweep.
            if t.grad_node is None:
                t.stop_gradient = False
        run_backward(outputs, grad_outputs, retain_graph=retain_graph)
        results: List[Optional[Tensor]] = []
        for t in inputs:
            g = t.grad
            if g is None and not allow_unused:
                raise InvalidArgumentError(
                    "an input tensor received no gradient; pass allow_unused=True "
                    "to get None for unused inputs"
                )
            results.append(g)
        return results
    finally:
        for t, (g, r, sg) in zip(inputs, saved):
            t._grad = g
            t.retain_grads_flag = r
            t.stop_gradient = sg
