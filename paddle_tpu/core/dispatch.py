"""Eager op dispatch.

TPU-native counterpart of the reference's dygraph dispatch path (SURVEY §3.1:
generated ``*_ad_func`` → PHI API → KernelFactory → kernel). Here an "op" is a
pure JAX function over arrays; dispatch (1) unwraps Tensor leaves, (2) decides
whether gradients must be recorded, (3) either calls the function directly
(XLA executes op-by-op with async dispatch — the DeviceContext-stream analog)
or routes through ``jax.vjp`` to capture residuals + the backward closure on a
``GradNode`` (≈ ``eager_gen.py:339-359`` node creation + ``SetGradOutMeta``).

The NaN/Inf debug scan (``FLAGS_check_nan_inf``) mirrors
``paddle/fluid/eager/nan_inf_utils.cc``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import autograd as _ag
from paddle_tpu.flags import GLOBAL_FLAGS

# FLAGS_check_nan_inf / _level cached in plain lists kept fresh by on_change
# listeners (the observability/metrics.py idiom, enforced as FD302): the scan
# gate runs on every op dispatch and must not take the registry lock.
_NAN_CHECK = [False]
_NAN_LEVEL = [0]


def _refresh_nan_check(value: Any) -> None:
    _NAN_CHECK[0] = bool(value)


def _refresh_nan_level(value: Any) -> None:
    _NAN_LEVEL[0] = int(value)


GLOBAL_FLAGS.on_change("check_nan_inf", _refresh_nan_check)
GLOBAL_FLAGS.on_change("check_nan_inf_level", _refresh_nan_level)
_NAN_CHECK[0] = bool(GLOBAL_FLAGS.get("check_nan_inf"))  # seeds FLAGS_ env var
_NAN_LEVEL[0] = int(GLOBAL_FLAGS.get("check_nan_inf_level"))


def _is_tensor(x: Any) -> bool:
    from paddle_tpu.core.tensor import Tensor

    return isinstance(x, Tensor)


def _differentiable(t: Any) -> bool:
    return (not t.stop_gradient) and jnp.issubdtype(jnp.dtype(t.dtype), jnp.inexact)


def _check_nan_inf(name: str, arrays: Sequence[Any]) -> None:
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(jnp.dtype(a.dtype), jnp.inexact):
            finite = bool(jnp.all(jnp.isfinite(a)))
            if not finite:
                level = _NAN_LEVEL[0]
                msg = f"NaN/Inf detected in output of op '{name}'"
                if level == 0:
                    raise FloatingPointError(msg)
                import logging

                logging.getLogger("paddle_tpu").warning(msg)


def call_op(name: str, fn: Callable, *args: Any, **kwargs: Any) -> Any:
    """Dispatch one op. ``fn`` is a pure function over jax arrays.

    Returns Tensor (or tuple/list of Tensors mirroring fn's output structure).
    """
    from paddle_tpu.core.tensor import Tensor

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    tensor_pos = [i for i, leaf in enumerate(leaves) if _is_tensor(leaf)]
    record = _ag.is_grad_enabled() and any(
        _differentiable(leaves[i]) for i in tensor_pos
    )

    # AMP autocast (O1): cast white/black-list op inputs at dispatch, the
    # analog of the reference's generated *_ad_func autocast prologue.
    datas = [leaves[i].data for i in tensor_pos]
    from paddle_tpu.amp.auto_cast import amp_cast_inputs, amp_enabled

    if amp_enabled():
        datas = list(amp_cast_inputs(name, datas))
    data_at = dict(zip(tensor_pos, datas))

    if not record:
        plain = list(leaves)
        for i in tensor_pos:
            plain[i] = data_at[i]
        a, k = jax.tree_util.tree_unflatten(treedef, plain)
        raw_out = fn(*a, **k)
        return _wrap_outputs(name, raw_out, node=None)

    diff_pos = [i for i in tensor_pos if _differentiable(leaves[i])]
    diff_tensors = [leaves[i] for i in diff_pos]

    # Close over raw arrays only (no Tensor objects): the node retains this
    # closure for create_graph re-differentiation, and holding Tensors here
    # would pin non-differentiable inputs' upstream tape alive.
    plain = list(leaves)
    for i in tensor_pos:
        plain[i] = data_at[i]

    def closed(*diff_arrays: Any) -> Any:
        rebuilt = list(plain)
        for pos, arr in zip(diff_pos, diff_arrays):
            rebuilt[pos] = arr
        a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
        return fn(*a, **k)

    primals = [data_at[i] for i in diff_pos]
    raw_out, vjp_fn = jax.vjp(closed, *primals)

    flat_out, out_treedef = jax.tree_util.tree_flatten(raw_out)
    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in flat_out]
    node = _ag.GradNode(
        name, vjp_fn, diff_tensors, out_avals, fwd_fn=closed, out_treedef=out_treedef
    )
    return _wrap_outputs(name, raw_out, node=node)


op_stats_hook: Optional[Callable] = None  # amp.debugging operator-stat collector


def _wrap_outputs(name: str, raw_out: Any, node: Optional[_ag.GradNode]) -> Any:
    from paddle_tpu.core.tensor import Tensor

    flat_out, out_treedef = jax.tree_util.tree_flatten(raw_out)
    if _NAN_CHECK[0]:
        _check_nan_inf(name, flat_out)
    if op_stats_hook is not None:
        op_stats_hook(name, flat_out)
    wrapped: List[Any] = []
    for i, o in enumerate(flat_out):
        t = Tensor(o, stop_gradient=(node is None))
        if node is not None:
            t._grad_node = node
            t._grad_output_index = i
            # Non-inexact outputs (e.g. argmax indices) carry no gradient.
            if not jnp.issubdtype(jnp.dtype(t.dtype), jnp.inexact):
                t.stop_gradient = True
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def defop(name: str) -> Callable[[Callable], Callable]:
    """Decorator: turn a pure jax-array function into an eager Tensor op.

    The wrapped function transparently accepts Tensors, numbers, numpy/jax
    arrays; when called with tracer inputs (inside paddle_tpu.jit capture) it
    behaves identically because dispatch only touches ``.data``.
    """

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            return call_op(name, fn, *args, **kwargs)

        wrapper.__paddle_tpu_op__ = name  # type: ignore[attr-defined]
        wrapper.raw_fn = fn  # type: ignore[attr-defined]
        return wrapper

    return deco
