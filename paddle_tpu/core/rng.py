"""RNG state management.

Counterpart of the reference's ``phi::Generator`` (``paddle/phi/core/generator.h``)
built on JAX's splittable PRNG: a process-global Generator owns a key and hands
out fresh subkeys per random op (the stateful-seed ↔ functional-key bridge).
``RNGStatesTracker`` (per-name states, used for tensor-parallel dropout seed
control) mirrors ``fleet/layers/mpu/random.py``.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np


class Generator:
    """Stateful wrapper over a splittable jax PRNG key."""

    def __init__(self, seed_: int = 0) -> None:
        self._lock = threading.Lock()
        self._seed = int(seed_)
        # Key creation is deferred: PRNGKey() is a device computation, and a
        # module-scope Generator would otherwise initialize the jax backend at
        # `import paddle_tpu` time (hanging imports when the TPU tunnel is
        # down, even for processes that never run a computation).
        self._key: Optional[jax.Array] = None

    def _ensure_key(self) -> jax.Array:
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def manual_seed(self, seed_: int) -> "Generator":
        with self._lock:
            self._seed = int(seed_)
            self._key = None
        return self

    def next_key(self) -> jax.Array:
        with self._lock:
            self._key, sub = jax.random.split(self._ensure_key())
            return sub

    def get_state(self) -> np.ndarray:
        with self._lock:
            return np.asarray(jax.random.key_data(self._ensure_key()))

    def set_state(self, state: Any) -> None:
        with self._lock:
            self._key = jax.random.wrap_key_data(
                jax.numpy.asarray(state, dtype=jax.numpy.uint32)
            )

    @property
    def initial_seed(self) -> int:
        return self._seed


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(seed_: int) -> Generator:
    """Set the global random seed (``paddle.seed`` parity)."""
    return _default_generator.manual_seed(seed_)


def next_key() -> jax.Array:
    return _default_generator.next_key()


def get_rng_state() -> np.ndarray:
    return _default_generator.get_state()


def set_rng_state(state: Any) -> None:
    _default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG states for parallel regions (TP-group dropout determinism).

    Reference: ``python/paddle/distributed/fleet/layers/mpu/random.py``
    ``RNGStatesTracker`` — e.g. 'global_seed' vs 'local_seed' so dropout masks
    are replicated across TP ranks where required and distinct where not.
    """

    def __init__(self) -> None:
        self._states: Dict[str, Generator] = {}

    def add(self, name: str, seed_: int) -> None:
        if name in self._states:
            raise ValueError(f"rng state '{name}' already exists")
        self._states[name] = Generator(seed_)

    def reset(self) -> None:
        self._states.clear()

    def get_states_tracker(self) -> Dict[str, np.ndarray]:
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states_tracker(self, states: Dict[str, Any]) -> None:
        for k, s in states.items():
            self._states.setdefault(k, Generator(0)).set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed") -> Iterator[None]:
        if name not in self._states:
            raise KeyError(f"unknown rng state '{name}'; add() it first")
        global _default_generator
        prev = _default_generator
        _default_generator = self._states[name]
        try:
            yield
        finally:
            _default_generator = prev


_global_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _global_tracker
