"""Device memory observability.

Counterpart of the reference's allocator stat registry
(``paddle/phi/core/memory/stats.h:126-133`` ``DeviceMemoryStat*``
peak/current accounting, ``FLAGS_log_memory_stats``) and the Python surface
``paddle.device.cuda.max_memory_allocated`` /
``memory_allocated``/``memory_reserved``
(``python/paddle/device/cuda/__init__.py``).

On TPU the numbers come straight from PJRT's per-device allocator
(``jax.Device.memory_stats()``: ``bytes_in_use``, ``peak_bytes_in_use``,
``bytes_limit`` …). Backends without allocator stats (the CPU test backend)
fall back to summing live ``jax.Array`` buffers on the device, with the peak
tracked at query points by this module. ``reset_max_memory_allocated`` resets
the module-side peak; the PJRT peak cannot be lowered from user code, so
after a reset the reported max is the high-water seen at subsequent queries.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import jax

__all__ = [
    "memory_stats",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "max_memory_reserved",
    "reset_max_memory_allocated",
    "compiled_memory_stats",
]

_lock = threading.Lock()
_peak_since_reset: Dict[int, int] = {}  # device id -> tracked high-water
_pjrt_peak_baseline: Dict[int, int] = {}  # subtracted after reset


def _resolve(device: Any = None) -> jax.Device:
    from paddle_tpu.core.device import Place, current_place

    if device is None:
        return current_place().jax_device()
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, Place):
        return device.jax_device()
    if isinstance(device, int):
        return jax.devices()[device]
    from paddle_tpu.core.device import _parse

    return _parse(device).jax_device()


def _live_bytes(dev: jax.Device) -> int:
    total = 0
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                if shard.device == dev:
                    total += shard.data.nbytes
        except Exception:  # deleted/donated buffers
            continue
    return total


def memory_stats(device: Any = None) -> Dict[str, int]:
    """Raw allocator stats for one device. PJRT-backed where available,
    else ``{"bytes_in_use": <live array bytes>}``."""
    dev = _resolve(device)
    stats = None
    if hasattr(dev, "memory_stats"):
        stats = dev.memory_stats()
    if not stats:
        stats = {"bytes_in_use": _live_bytes(dev)}
    return dict(stats)


def memory_allocated(device: Any = None) -> int:
    """Bytes currently allocated on the device
    (``paddle.device.cuda.memory_allocated`` analog)."""
    dev = _resolve(device)
    current = int(memory_stats(dev).get("bytes_in_use", 0))
    with _lock:
        key = id(dev)
        _peak_since_reset[key] = max(_peak_since_reset.get(key, 0), current)
    return current


def max_memory_allocated(device: Any = None) -> int:
    """Peak bytes allocated (``max_memory_allocated`` /
    ``DeviceMemoryStatPeakValue`` analog, stats.h:126)."""
    dev = _resolve(device)
    key = id(dev)
    stats = memory_stats(dev)
    current = int(stats.get("bytes_in_use", 0))
    pjrt_peak = int(stats.get("peak_bytes_in_use", 0)) - _pjrt_peak_baseline.get(key, 0)
    with _lock:
        tracked = max(_peak_since_reset.get(key, 0), current, pjrt_peak)
        _peak_since_reset[key] = tracked
    return tracked


def memory_reserved(device: Any = None) -> int:
    """Bytes reserved by the allocator pool (limit-aware backends)."""
    stats = memory_stats(device)
    return int(stats.get("bytes_reserved", stats.get("pool_bytes", stats.get("bytes_in_use", 0))))


def max_memory_reserved(device: Any = None) -> int:
    stats = memory_stats(device)
    return int(stats.get("peak_bytes_reserved", stats.get("peak_bytes_in_use", 0)) or max_memory_allocated(device))


def reset_max_memory_allocated(device: Any = None) -> None:
    """Restart peak tracking (``paddle.device.cuda.reset_max_memory_allocated``)."""
    dev = _resolve(device)
    key = id(dev)
    stats = memory_stats(dev)
    with _lock:
        _peak_since_reset[key] = int(stats.get("bytes_in_use", 0))
        _pjrt_peak_baseline[key] = int(stats.get("peak_bytes_in_use", 0))


def compiled_memory_stats(compiled: Any) -> Dict[str, int]:
    """Per-program memory footprint of a compiled XLA executable —
    ``jit(f).lower(...).compile().memory_analysis()`` distilled. The TPU
    analog of the reference's executor memory accounting
    (``executor_statistics.cc``): what HBM one step of this program needs."""
    ma = compiled.memory_analysis() if hasattr(compiled, "memory_analysis") else compiled
    out = {}
    for k in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "peak_memory_in_bytes",
        "generated_code_size_in_bytes",
    ):
        out[k] = int(getattr(ma, k, 0))
    if not hasattr(ma, "peak_memory_in_bytes"):
        # jax 0.4.37's CompiledMemoryStats predates the PJRT peak field;
        # arguments + outputs + temps are simultaneously live at the peak of
        # one program execution — minus aliased bytes, where a donated input's
        # buffer IS the output (counting both would overstate peak by the
        # whole donated KV pool on the engine's decode step).
        out["peak_memory_in_bytes"] = max(
            out["argument_size_in_bytes"]
            + out["output_size_in_bytes"]
            + out["temp_size_in_bytes"]
            - out["alias_size_in_bytes"],
            0,
        )
    return out
