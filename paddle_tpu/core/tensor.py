"""The Tensor type: a jax.Array plus autograd metadata.

TPU-native counterpart of the reference's eager Tensor
(``paddle/fluid/pybind/eager_method.cc`` surface over ``phi::DenseTensor``,
``paddle/phi/core/dense_tensor.h:37``): the device buffer is a ``jax.Array``
(PJRT buffer, async dispatch, XLA-owned layout), and autograd metadata
(``stop_gradient``, ``grad``, grad node edge) mirrors ``egr::AutogradMeta``.

Ops attach themselves as methods via ``register_tensor_method`` — the analog of
the generated pybind method table.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.errors import InvalidArgumentError, PreconditionNotMetError

_name_counter = itertools.count()


def _auto_name(prefix: str = "generated_tensor") -> str:
    return f"{prefix}_{next(_name_counter)}"


class Tensor:
    __array_priority__ = 100  # win binary-op dispatch vs numpy arrays

    def __init__(
        self,
        data: Any = None,
        dtype: Any = None,
        place: Any = None,
        stop_gradient: bool = True,
        name: Optional[str] = None,
    ) -> None:
        if isinstance(data, Tensor):
            data = data._data
        if data is None:
            data = jnp.zeros((), jnp.float32)
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data, dtype=convert_dtype(dtype) if dtype else None)
        elif dtype is not None and jnp.dtype(data.dtype) != jnp.dtype(convert_dtype(dtype)):
            data = data.astype(convert_dtype(dtype))
        if place is not None and not isinstance(data, jax.core.Tracer):
            data = jax.device_put(data, place.jax_device())
        self._data = data
        self.stop_gradient = bool(stop_gradient)
        self._grad: Optional["Tensor"] = None
        self._grad_node: Optional[_ag.GradNode] = None
        self._grad_output_index: int = 0
        self.retain_grads_flag: bool = False
        self._backward_hooks: List[Callable] = []
        # bumped by in-place mutation; create_graph backward checks it
        # (reference: tensor version counters, eager/tensor_wrapper.h)
        self._version: int = 0
        self.name = name or _auto_name()
        self.persistable = False

    # -- raw buffer access ----------------------------------------------------
    @property
    def data(self) -> jax.Array:
        """The underlying jax.Array (device buffer)."""
        return self._data

    # -- metadata -------------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def dtype(self) -> Any:
        return self._data.dtype

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self) -> Any:
        from paddle_tpu.core.device import CPUPlace, TPUPlace

        if isinstance(self._data, jax.core.Tracer):
            return None
        dev = next(iter(self._data.devices()))
        if dev.platform in ("tpu", "axon"):
            return TPUPlace(dev.id)
        return CPUPlace()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def grad_node(self) -> Optional[_ag.GradNode]:
        return self._grad_node

    @property
    def grad_output_index(self) -> int:
        return self._grad_output_index

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    # -- autograd -------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value: Optional["Tensor"]) -> None:
        self._grad = value

    def backward(self, grad_tensor: Any = None, retain_graph: bool = False) -> None:
        """Run reverse-mode autodiff from this tensor (``Tensor.backward`` parity;
        reference entry ``paddle/fluid/pybind/eager_functions.cc:145``)."""
        grads = None if grad_tensor is None else [grad_tensor]
        _ag.run_backward([self], grads, retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self._grad = None

    clear_gradient = clear_grad

    def retain_grads(self) -> None:
        self.retain_grads_flag = True

    def register_hook(self, hook: Callable) -> "_HookHandle":
        self._backward_hooks.append(hook)
        return _HookHandle(self, hook)

    def _apply_backward_hooks(self, g: Any) -> Any:
        if not self._backward_hooks:
            return g
        keep_tensor = isinstance(g, Tensor)
        gt = g if keep_tensor else Tensor(g)
        for hook in self._backward_hooks:
            out = hook(gt)
            if out is not None:
                if not isinstance(out, Tensor) and keep_tensor:
                    # under a create_graph sweep a raw-array hook result has
                    # no tape: rewrapping it would silently detach the
                    # higher-order gradient through this hook — warn once
                    # (hooks must return Tensors to stay differentiable)
                    import warnings

                    warnings.warn(
                        "a backward hook returned a raw array during a "
                        "create_graph sweep; the higher-order tape is detached "
                        "through it. Return a Tensor to keep it differentiable.",
                        stacklevel=2,
                    )
                gt = out if isinstance(out, Tensor) else Tensor(out)
        return gt if keep_tensor else gt._data

    def _accumulate_grad(self, g: Any) -> None:
        # Grads accumulate in the parameter's dtype (AMP-cast cotangents are
        # upcast here, mirroring the cast-op grad in the reference's O1 path).
        if isinstance(g, Tensor) and g.grad_node is not None:
            # create_graph sweep: preserve the grad's own tape so it can be
            # differentiated again (cast/add dispatched, not detached).
            if jnp.dtype(g.dtype) != jnp.dtype(self._data.dtype):
                g = g.astype(self._data.dtype)
            self._grad = g if self._grad is None else self._grad + g
            return
        if isinstance(g, Tensor):
            g = g._data
        if hasattr(g, "dtype") and jnp.dtype(g.dtype) != jnp.dtype(self._data.dtype):
            g = g.astype(self._data.dtype)
        if self._grad is None:
            self._grad = Tensor(g, stop_gradient=True, name=self.name + "@GRAD")
        elif self._grad.grad_node is not None:
            # Existing grad carries a tape (create_graph): add via dispatch so
            # the taped component stays differentiable.
            self._grad = self._grad + Tensor(g, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._data + g, stop_gradient=True, name=self.name + "@GRAD")

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    # -- conversion -----------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype: Any = None) -> np.ndarray:
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def item(self, *args: int) -> Any:
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self) -> Any:
        return self.numpy().tolist()

    def astype(self, dtype: Any) -> "Tensor":
        from paddle_tpu.core.dispatch import call_op

        target = convert_dtype(dtype)
        return call_op("cast", lambda x: x.astype(target), self)

    cast = astype

    def to(self, *args: Any, **kwargs: Any) -> "Tensor":
        """``Tensor.to(device|dtype)`` subset parity."""
        from paddle_tpu.core.device import _parse

        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and (a in ("cpu",) or ":" in a or a in ("tpu", "gpu")):
                place = _parse(a)
                out = Tensor(
                    jax.device_put(out._data, place.jax_device()),
                    stop_gradient=out.stop_gradient,
                    name=out.name,
                )
            else:
                out = out.astype(a)
        return out

    def cpu(self) -> "Tensor":
        return self.to("cpu")

    def clone(self) -> "Tensor":
        from paddle_tpu.core.dispatch import call_op

        return call_op("clone", lambda x: x + jnp.zeros((), x.dtype), self)

    def pin_memory(self) -> "Tensor":
        return self

    def contiguous(self) -> "Tensor":
        return self

    def is_contiguous(self) -> bool:
        return True

    # -- mutation (used by optimizers / loading under no_grad) ---------------
    def set_value(self, value: Any) -> None:
        new = value._data if isinstance(value, Tensor) else jnp.asarray(value, dtype=self.dtype)
        if tuple(new.shape) != tuple(self._data.shape):
            raise InvalidArgumentError(
                f"set_value shape mismatch: tensor {tuple(self._data.shape)} vs value {tuple(new.shape)}"
            )
        self._data = new.astype(self._data.dtype)
        self._version += 1

    def copy_(self, other: Any) -> "Tensor":
        self.set_value(other)
        return self

    def _replace_(self, new: "Tensor") -> None:
        """Adopt another tensor's buffer + tape position (in-place op support).

        When the adopting op recorded ``self`` as its input, that recording
        must keep pointing at the PRE-mutation tape position — otherwise the
        node's input would resolve to the node itself (a cycle) and the
        history feeding the in-place op would be orphaned. An alias tensor
        carries the old buffer + old grad node into the recording (the
        reference's TensorWrapper keeps the pre-bump version the same way).
        """
        node = new._grad_node
        if node is not None and not getattr(node, "released", True):
            alias: Optional[Tensor] = None
            for i, t in enumerate(node.input_tensors):
                if t is self:
                    if alias is None:
                        alias = Tensor(self._data, stop_gradient=self.stop_gradient)
                        alias._grad_node = self._grad_node
                        alias._grad_output_index = self._grad_output_index
                    node.input_tensors[i] = alias
        self._data = new._data
        self._grad_node = new._grad_node
        self._grad_output_index = new._grad_output_index
        self.stop_gradient = new.stop_gradient
        self._version += 1

    # -- indexing -------------------------------------------------------------
    def __getitem__(self, index: Any) -> "Tensor":
        from paddle_tpu.core.dispatch import call_op

        def gather(x: Any, idx: Any) -> Any:
            return x[idx]

        return call_op("getitem", gather, self, _unwrap_index(index))

    def __setitem__(self, index: Any, value: Any) -> None:
        from paddle_tpu.core.dispatch import call_op

        def scatter(x: Any, idx: Any, v: Any) -> Any:
            return x.at[idx].set(v.astype(x.dtype) if hasattr(v, "astype") else v)

        new = call_op("setitem", scatter, self, _unwrap_index(index), value)
        self._replace_(new)

    def __iter__(self) -> Any:
        for i in range(len(self)):
            yield self[i]

    # -- scalars / truthiness -------------------------------------------------
    def __bool__(self) -> bool:
        if self.size != 1:
            raise PreconditionNotMetError(
                "truth value of a multi-element Tensor is ambiguous; use .any()/.all()"
            )
        return bool(self.numpy().reshape(()))

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __index__(self) -> int:
        return int(self.item())

    __hash__ = object.__hash__

    def __repr__(self) -> str:
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            value = np.array2string(self.numpy(), precision=6, separator=", ", threshold=64)
        except Exception:  # repr must never raise: traced/donated/deleted buffers
            value = "<traced>"
        return (
            f"Tensor(shape={self.shape}, dtype={jnp.dtype(self.dtype).name}{grad_info},\n"
            f"       {value})"
        )

    # -- dunder arithmetic: lazily bound to ops.math --------------------------
    def _binop(self, opname: str, other: Any, reverse: bool = False) -> "Tensor":
        from paddle_tpu.ops import math as _math

        fn = getattr(_math, opname)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o: Any) -> "Tensor":
        return self._binop("add", o)

    def __radd__(self, o: Any) -> "Tensor":
        return self._binop("add", o, True)

    def __sub__(self, o: Any) -> "Tensor":
        return self._binop("subtract", o)

    def __rsub__(self, o: Any) -> "Tensor":
        return self._binop("subtract", o, True)

    def __mul__(self, o: Any) -> "Tensor":
        return self._binop("multiply", o)

    def __rmul__(self, o: Any) -> "Tensor":
        return self._binop("multiply", o, True)

    def __truediv__(self, o: Any) -> "Tensor":
        return self._binop("divide", o)

    def __rtruediv__(self, o: Any) -> "Tensor":
        return self._binop("divide", o, True)

    def __floordiv__(self, o: Any) -> "Tensor":
        return self._binop("floor_divide", o)

    def __rfloordiv__(self, o: Any) -> "Tensor":
        return self._binop("floor_divide", o, True)

    def __mod__(self, o: Any) -> "Tensor":
        return self._binop("remainder", o)

    def __rmod__(self, o: Any) -> "Tensor":
        return self._binop("remainder", o, True)

    def __pow__(self, o: Any) -> "Tensor":
        return self._binop("pow", o)

    def __rpow__(self, o: Any) -> "Tensor":
        return self._binop("pow", o, True)

    def __matmul__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import linalg as _linalg

        return _linalg.matmul(self, o)

    def __rmatmul__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import linalg as _linalg

        return _linalg.matmul(o, self)

    def __neg__(self) -> "Tensor":
        return self._binop("multiply", -1)

    def __abs__(self) -> "Tensor":
        from paddle_tpu.ops import math as _math

        return _math.abs(self)

    def __eq__(self, o: Any) -> "Tensor":  # type: ignore[override]
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.equal(self, o)

    def __ne__(self, o: Any) -> "Tensor":  # type: ignore[override]
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.not_equal(self, o)

    def __lt__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.less_than(self, o)

    def __le__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.less_equal(self, o)

    def __gt__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.greater_than(self, o)

    def __ge__(self, o: Any) -> "Tensor":
        from paddle_tpu.ops import comparison as _cmp

        return _cmp.greater_equal(self, o)

    def __invert__(self) -> "Tensor":
        from paddle_tpu.ops import logic as _logic

        return _logic.logical_not(self)

    @property
    def T(self) -> "Tensor":  # noqa: N802
        from paddle_tpu.ops import linalg as _linalg

        return _linalg.t(self)


class _HookHandle:
    def __init__(self, tensor: Tensor, hook: Callable) -> None:
        self._tensor = tensor
        self._hook = hook

    def remove(self) -> None:
        if self._hook in self._tensor._backward_hooks:
            self._tensor._backward_hooks.remove(self._hook)


def _unwrap_index(index: Any) -> Any:
    """Pass Tensors in an index expression through as dispatch args."""
    if isinstance(index, tuple):
        return tuple(_unwrap_index(i) for i in index)
    if isinstance(index, list):
        return jnp.asarray(index)
    return index


class Parameter(Tensor):
    """A trainable Tensor (``paddle.create_parameter`` / ``EagerParamBase``)."""

    def __init__(
        self,
        data: Any = None,
        dtype: Any = None,
        name: Optional[str] = None,
        trainable: bool = True,
    ) -> None:
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name or _auto_name("param"))
        self.trainable = trainable
        self.persistable = True

    def __repr__(self) -> str:
        return "Parameter containing:\n" + super().__repr__()


# -- method registration ------------------------------------------------------
def register_tensor_method(name: str, fn: Callable) -> None:
    """Attach an op as a Tensor method (the generated-pybind-methods analog)."""
    if not hasattr(Tensor, name):
        setattr(Tensor, name, fn)
