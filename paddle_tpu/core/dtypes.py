"""Canonical dtypes.

Counterpart of the reference's ``phi::DataType`` (``paddle/phi/common/data_type.h``)
— here dtypes ARE jax/numpy dtypes, so everything interops with jnp directly.
"""

from __future__ import annotations

from typing import Any, Union

import jax.numpy as jnp
import numpy as np

dtype = jnp.dtype

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}

FLOATING = (jnp.bfloat16, jnp.float16, jnp.float32, jnp.float64)
INTEGER = (jnp.int8, jnp.int16, jnp.int32, jnp.int64, jnp.uint8)


def convert_dtype(d: Any) -> Any:
    """Normalize a dtype-ish (str, np.dtype, jnp dtype) to a jnp scalar type."""
    if d is None:
        return None
    if isinstance(d, str):
        key = d.lower().removeprefix("paddle.")
        if key in _ALIASES:
            return _ALIASES[key]
        return jnp.dtype(key).type
    if isinstance(d, jnp.dtype) or isinstance(d, np.dtype):
        return jnp.dtype(d).type
    return jnp.dtype(d).type


def is_floating_point(d: Any) -> bool:
    return jnp.issubdtype(jnp.dtype(convert_dtype(d)), jnp.floating)


def is_integer(d: Any) -> bool:
    return jnp.issubdtype(jnp.dtype(convert_dtype(d)), jnp.integer)


def is_complex(d: Any) -> bool:
    return jnp.issubdtype(jnp.dtype(convert_dtype(d)), jnp.complexfloating)
