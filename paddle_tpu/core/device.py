"""Places / devices.

Counterpart of the reference's ``phi::Place`` + device management
(``paddle/phi/backends/device_manager.h:134``). On TPU the PJRT client owns
devices; a Place is a thin handle onto a ``jax.Device``.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Union

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0) -> None:
        self.device_id = int(device_id)

    def __repr__(self) -> str:
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Place)
            and other.device_type == self.device_type
            and other.device_id == self.device_id
        )

    def __hash__(self) -> int:
        return hash((self.device_type, self.device_id))

    def jax_device(self) -> jax.Device:
        devices = [d for d in jax.devices() if _platform_matches(d.platform, self.device_type)]
        if not devices:
            # Fall back to whatever the default backend exposes (e.g. tests on CPU).
            devices = jax.devices()
        return devices[self.device_id % len(devices)]


def _platform_matches(platform: str, device_type: str) -> bool:
    if device_type == "tpu":
        # The lab tunnel exposes the TPU chip under the experimental 'axon' platform.
        return platform in ("tpu", "axon")
    return platform == device_type


class TPUPlace(Place):
    device_type = "tpu"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self) -> None:
        super().__init__(0)


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0) -> None:
        super().__init__(device_id)
        self.device_type = device_type


_state = threading.local()


def _default_device_str() -> str:
    platform = jax.default_backend()
    if platform in ("tpu", "axon"):
        return "tpu:0"
    return "cpu"


def set_device(device: str) -> Place:
    """Set the active device, e.g. ``set_device("tpu:0")``. Mirrors ``paddle.set_device``."""
    place = _parse(device)
    _state.place = place
    return place


def get_device() -> str:
    place = getattr(_state, "place", None)
    if place is None:
        return _default_device_str()
    if isinstance(place, CPUPlace):
        return "cpu"
    return f"{place.device_type}:{place.device_id}"


def current_place() -> Place:
    place = getattr(_state, "place", None)
    if place is not None:
        return place
    return _parse(_default_device_str())


def _parse(device: Union[str, Place]) -> Place:
    if isinstance(device, Place):
        return device
    spec = device.lower()
    if spec == "cpu":
        return CPUPlace()
    kind, _, idx = spec.partition(":")
    device_id = int(idx) if idx else 0
    if kind in ("tpu", "gpu", "xpu", "axon"):
        # gpu/xpu names are accepted for script compat and map onto the accelerator.
        return TPUPlace(device_id)
    return CustomPlace(kind, device_id)


class device:  # noqa: N801 - mirrors paddle.device module-as-namespace usage
    set_device = staticmethod(set_device)
    get_device = staticmethod(get_device)

    @staticmethod
    def device_count() -> int:
        return len(jax.devices())

    @staticmethod
    def is_compiled_with_cuda() -> bool:
        return False

    @staticmethod
    def synchronize() -> None:
        """Block until all enqueued work is done (async dispatch barrier)."""
        import jax as _jax

        (_jax.device_put(0) + 0).block_until_ready()

    # memory observability (reference stats.h:126 + paddle.device.cuda.*)
    @staticmethod
    def memory_stats(device_: object = None):
        from paddle_tpu.core.memory import memory_stats as _ms

        return _ms(device_)

    @staticmethod
    def memory_allocated(device_: object = None) -> int:
        from paddle_tpu.core.memory import memory_allocated as _ma

        return _ma(device_)

    @staticmethod
    def max_memory_allocated(device_: object = None) -> int:
        from paddle_tpu.core.memory import max_memory_allocated as _mma

        return _mma(device_)

    @staticmethod
    def memory_reserved(device_: object = None) -> int:
        from paddle_tpu.core.memory import memory_reserved as _mr

        return _mr(device_)

    @staticmethod
    def max_memory_reserved(device_: object = None) -> int:
        from paddle_tpu.core.memory import max_memory_reserved as _mmr

        return _mmr(device_)

    @staticmethod
    def reset_max_memory_allocated(device_: object = None) -> None:
        from paddle_tpu.core.memory import reset_max_memory_allocated as _r

        _r(device_)

    class cuda:  # noqa: N801 - paddle.device.cuda.* script compatibility
        """Accelerator-memory API under the reference's ``cuda`` name; maps
        onto the PJRT device (TPU here) so existing scripts keep working.
        Methods are aliased from ``device`` below — one implementation."""


# paddle.device.cuda.* == paddle.device.* (single set of bindings)
for _name in (
    "memory_stats",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "max_memory_reserved",
    "reset_max_memory_allocated",
    "synchronize",
):
    setattr(device.cuda, _name, getattr(device, _name))
del _name
