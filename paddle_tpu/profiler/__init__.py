"""Profiler (reference ``python/paddle/profiler``, SURVEY §5.1).

TPU-native: host spans via ``jax.profiler.TraceAnnotation`` (XPlane/TraceMe —
the RecordEvent analog) + device traces via ``jax.profiler`` sessions, exported
to TensorBoard/perfetto; plus a pure-python host-event recorder that writes
chrome://tracing JSON like the reference's ``chrometracing_logger.cc``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

__all__ = [
    "Profiler",
    "ProfilerState",
    "ProfilerTarget",
    "RecordEvent",
    "make_scheduler",
    "export_chrome_tracing",
    "load_profiler_result",
    "benchmark",
]

from paddle_tpu.profiler.timer import benchmark  # noqa: E402,F401


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class _HostEventRecorder:
    """Reference ``host_event_recorder.h`` analog. Spans go to the native C++
    recorder (``cpp/host_tracer.cpp``) when built — no allocation per span on
    the hot path — with this python buffer as fallback."""

    def __init__(self) -> None:
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._enabled = False
        self._native = None
        try:
            from paddle_tpu.core.native import load_native

            # build=False: never compile C++ during `import paddle_tpu`
            self._native = load_native(build=False)
        except Exception:  # native extension optional: pure-python recorder suffices
            self._native = None

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, on: bool) -> None:
        self._enabled = on
        if self._native is not None:
            self._native.het_enable(1 if on else 0)

    def add(self, name: str, start_us: float, end_us: float, tid: int) -> None:
        if self._native is not None:
            self._native.het_record(name.encode(), start_us, end_us - start_us, tid)
            return
        with self._lock:
            self._events.append(
                {"name": name, "ph": "X", "ts": start_us, "dur": end_us - start_us,
                 "pid": os.getpid(), "tid": tid}
            )

    def drain(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        if self._native is not None:
            cap = 1 << 20
            while True:
                import ctypes

                buf = ctypes.create_string_buffer(cap)
                n = self._native.het_drain_json(buf, cap, os.getpid())
                if n < 0:
                    cap = -n
                    continue
                events.extend(json.loads(buf.value.decode()))
                break
        with self._lock:
            events_py, self._events = self._events, []
        return events + events_py


_recorder = _HostEventRecorder()


class RecordEvent:
    """RAII host span (reference ``paddle/phi/api/profiler/event_tracing.h``
    RecordEvent). Also forwards to jax TraceAnnotation so spans appear in XLA
    device traces."""

    def __init__(self, name: str, event_type: Any = None) -> None:
        self.name = name
        self._start: Optional[float] = None
        self._jax_ann = None

    def begin(self) -> None:
        self._start = time.perf_counter() * 1e6
        try:
            import jax.profiler

            self._jax_ann = jax.profiler.TraceAnnotation(self.name)
            self._jax_ann.__enter__()
        except Exception:  # device annotation is best-effort; host span still recorded
            self._jax_ann = None

    def end(self) -> None:
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        if self._start is not None and _recorder.enabled:
            _recorder.add(self.name, self._start, time.perf_counter() * 1e6, threading.get_ident())
        self._start = None

    def __enter__(self) -> "RecordEvent":
        self.begin()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.end()


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Window scheduler (reference ``profiler.py`` make_scheduler)."""

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    def handler(prof: "Profiler") -> None:
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'paddle_tpu'}_{int(time.time())}.pt.trace.json"
        )
        prof.export(fname, format="json")

    return handler


class Profiler:
    """Reference ``python/paddle/profiler/profiler.py:358`` Profiler parity:
    state machine + scheduler windows + chrome export; device-side capture via
    jax.profiler when a trace dir is configured."""

    def __init__(
        self,
        targets: Optional[Iterable[ProfilerTarget]] = None,
        scheduler: Any = None,
        on_trace_ready: Optional[Callable] = None,
        record_shapes: bool = False,
        profile_memory: bool = False,
        timer_only: bool = False,
        emit_nvtx: bool = False,
        custom_device_types: Any = None,
        with_flops: bool = False,
    ) -> None:
        if isinstance(scheduler, tuple):
            start, end = scheduler
            self._schedule = make_scheduler(closed=start, ready=0, record=end - start, repeat=1)
        elif callable(scheduler):
            self._schedule = scheduler
        else:
            self._schedule = lambda step: ProfilerState.RECORD
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._events: List[Dict[str, Any]] = []
        self._timer_only = timer_only
        self._profile_memory = profile_memory
        self._jax_dir: Optional[str] = None

    def start(self) -> None:
        self._state = self._schedule(self._step)
        if self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recorder.enabled = True
        # HBM accounting across the profiled region (reference
        # DeviceMemoryStat peak tracking, stats.h:126). Opt-in via
        # profile_memory: the reset restarts the PROCESS-WIDE interval
        # tracker, which must not silently clobber a user's own measurement.
        if self._profile_memory:
            try:
                from paddle_tpu.core.memory import (
                    memory_allocated,
                    reset_max_memory_allocated,
                )

                reset_max_memory_allocated()
                self.memory_at_start = memory_allocated()
            except Exception:  # allocator stats unavailable on this backend
                self.memory_at_start = 0

    def stop(self) -> None:
        _recorder.enabled = False
        self._events.extend(_recorder.drain())
        try:
            from paddle_tpu.core.memory import max_memory_allocated, memory_allocated

            # peak since the profiler's reset (profile_memory=True) or the
            # process-wide peak (still useful, never destructive)
            self.peak_memory_allocated = max_memory_allocated()
            self.memory_at_stop = memory_allocated()
        except Exception:  # allocator stats unavailable on this backend
            self.peak_memory_allocated = 0
            self.memory_at_stop = 0
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        self._state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None) -> None:
        self._events.extend(_recorder.drain())
        self._step += 1
        prev = self._state
        self._state = self._schedule(self._step)
        if self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recorder.enabled = True
        elif prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recorder.enabled = False
            if self._state == ProfilerState.CLOSED and self._on_trace_ready is not None:
                self._on_trace_ready(self)

    def __enter__(self) -> "Profiler":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def export(self, path: str, format: str = "json") -> None:  # noqa: A002
        events = self._events + _recorder.drain()
        try:
            # metrics snapshots taken via observability.write_snapshot_jsonl
            # appear as instant events on the same (perf_counter) timeline,
            # linking each snapshot file/seq into the span stream
            from paddle_tpu.observability.exporters import drain_trace_events

            events = events + drain_trace_events()
        except ImportError:  # exporters unavailable mid-teardown: spans still export
            pass
        try:
            # request/engine spans from the distributed tracer land on the
            # same perf_counter timeline as RecordEvent spans, so one chrome
            # trace shows a request's phases against the recorded host spans
            from paddle_tpu.observability.tracing import GLOBAL_TRACER

            events = events + GLOBAL_TRACER.drain_chrome_events()
        except ImportError:  # tracing unavailable mid-teardown: spans still export
            pass
        try:
            # devprof counter tracks (per-category device ms + segment split
            # per sampled step) ride the same perf_counter timeline as "C"
            # events, so the attribution overlays the span stream
            from paddle_tpu.observability import devprof as _devprof

            events = events + _devprof.drain_chrome_events()
        except ImportError:  # devprof unavailable mid-teardown
            pass
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def summary(self, sorted_by: Any = None, op_detail: bool = True, thread_sep: bool = False, time_unit: str = "ms") -> str:
        events = self._events
        agg: Dict[str, Tuple[int, float]] = {}
        for e in events:
            cnt, dur = agg.get(e["name"], (0, 0.0))
            agg[e["name"]] = (cnt + 1, dur + e["dur"])
        lines = [f"{'Name':<50} {'Calls':>8} {'Total(ms)':>12}"]
        for name, (cnt, dur) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<50} {cnt:>8} {dur / 1000.0:>12.3f}")
        return "\n".join(lines)


def load_profiler_result(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
