"""Throughput benchmark timer.

Reference: ``python/paddle/profiler/timer.py`` — ``benchmark()`` singleton
driven by hooks (``begin``/``step``/``end``) reporting reader cost, batch
cost, ips (items per second) with warmup-step exclusion.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

__all__ = ["benchmark", "Benchmark"]


class _Stat:
    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0
        self.window = []

    def add(self, v: float) -> None:
        self.total += v
        self.count += 1
        self.window.append(v)
        if len(self.window) > 100:
            self.window.pop(0)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    @property
    def smoothed(self) -> float:
        return sum(self.window) / len(self.window) if self.window else 0.0


class Benchmark:
    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._last_step_t: Optional[float] = None
        self._last_reader_t: Optional[float] = None
        self.batch_cost = _Stat()
        self.reader_cost = _Stat()
        self.ips = _Stat()
        self._num_samples: Optional[int] = None
        self._warmup = 10
        self._steps = 0

    def begin(self) -> None:
        self.reset()
        self._last_step_t = time.perf_counter()

    def before_reader(self) -> None:
        self._last_reader_t = time.perf_counter()

    def after_reader(self) -> None:
        if self._last_reader_t is not None and self._steps >= self._warmup:
            self.reader_cost.add(time.perf_counter() - self._last_reader_t)

    def step(self, num_samples: Optional[int] = None) -> None:
        now = time.perf_counter()
        self._steps += 1
        if self._last_step_t is not None and self._steps > self._warmup:
            dt = now - self._last_step_t
            self.batch_cost.add(dt)
            if num_samples:
                self.ips.add(num_samples / dt)
        self._last_step_t = now

    def end(self) -> Dict[str, float]:
        return self.step_info()

    def step_info(self, unit: str = "samples") -> Dict[str, float]:
        return {
            "reader_cost": self.reader_cost.smoothed,
            "batch_cost": self.batch_cost.smoothed,
            "ips": self.ips.smoothed,
            "steps": self._steps,
        }


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    """The global throughput meter (reference ``timer.py benchmark()``)."""
    return _benchmark
