"""Process-global flag registry.

TPU-native counterpart of the reference's flag system (``paddle/common/flags.cc``,
179 ``PHI_DEFINE_EXPORTED_*`` flags; registry macros ``paddle/common/flags.h:93``):
a typed registry of named flags, settable programmatically via
``paddle_tpu.set_flags`` / readable via ``get_flags``, with ``FLAGS_<name>``
environment variables honoured at first read (matching the reference's env-var
export convention).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Union


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    help: str
    value: Any = None
    env_read: bool = False


class FlagRegistry:
    """Typed flag registry; thread-safe; env ``FLAGS_<name>`` seeds the value."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.RLock()
        self._listeners: Dict[str, List[Callable[[Any], None]]] = {}

    def define(self, name: str, type_: type, default: Any, help_: str = "") -> None:
        with self._lock:
            if name in self._flags:
                raise ValueError(f"flag '{name}' already defined")
            self._flags[name] = _Flag(name=name, type=type_, default=default, help=help_, value=default)

    def on_change(self, name: str, callback: Callable[[Any], None]) -> None:
        """Register a callback fired with the new value whenever ``name`` is
        set (programmatically or by env seeding at first read). Lets hot paths
        cache a flag in a plain local instead of taking the registry lock per
        read — the metrics layer's near-zero-overhead gate."""
        with self._lock:
            self._listeners.setdefault(name, []).append(callback)

    def _notify(self, flag: _Flag) -> None:
        for cb in self._listeners.get(flag.name, ()):
            cb(flag.value)

    def _coerce(self, flag: _Flag, value: Any) -> Any:
        if flag.type is bool:
            if isinstance(value, str):
                return value.strip().lower() in ("1", "true", "yes", "on")
            return bool(value)
        return flag.type(value)

    def _maybe_read_env(self, flag: _Flag) -> None:
        if not flag.env_read:
            # mark BEFORE notifying: a listener that reads the flag back
            # (re-entrant under the RLock) must not re-enter seeding
            flag.env_read = True
            env = os.environ.get(f"FLAGS_{flag.name}")
            if env is not None:
                try:
                    flag.value = self._coerce(flag, env)
                except (TypeError, ValueError) as exc:
                    # un-mark so the error re-fires on EVERY read: if the first
                    # get() happens inside someone's broad except, the flag
                    # must not silently serve its default forever after
                    flag.env_read = False
                    # env seeding happens at the first get() of the flag, which
                    # can be deep inside unrelated code — name the flag and the
                    # env var so the malformed value is findable
                    raise ValueError(
                        f"invalid value {env!r} in environment variable "
                        f"FLAGS_{flag.name} for flag '{flag.name}' "
                        f"(expected {flag.type.__name__})"
                    ) from exc
                self._notify(flag)

    def get(self, name: str) -> Any:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"unknown flag '{name}'; known flags: {sorted(self._flags)}")
            flag = self._flags[name]
            self._maybe_read_env(flag)
            return flag.value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"unknown flag '{name}'")
            flag = self._flags[name]
            flag.env_read = True
            try:
                flag.value = self._coerce(flag, value)
            except (TypeError, ValueError) as exc:
                raise ValueError(
                    f"invalid value {value!r} for flag '{name}' "
                    f"(expected {flag.type.__name__})"
                ) from exc
            self._notify(flag)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._flags)


GLOBAL_FLAGS = FlagRegistry()


def _define_builtin_flags() -> None:
    d = GLOBAL_FLAGS.define
    d("check_nan_inf", bool, False, "Scan op outputs for NaN/Inf after every eager op (debug).")
    d("check_nan_inf_level", int, 0, "0: raise on nan/inf; 1: warn; 3: collect stats only.")
    d("eager_op_cache_size", int, 4096, "Max entries in the eager per-op compiled-executable cache.")
    d("use_pallas_attention", bool, True, "Use Pallas flash-attention kernels on TPU when applicable.")
    d("use_pallas_fused", bool, True, "Use Pallas fused rms_norm/rope kernels on TPU when applicable.")
    d("use_pallas_paged_attention", bool, True, "Use the Pallas block-table flash-decode kernel on TPU.")
    d("use_fused_decode_layer", bool, True, "Fuse the decode step's per-layer epilogues (RoPE into the paged-attention kernel's block walk, residual-add + norm pairs into one kernel, token embedding gather + first norm at the step entry) behind one flag: fewer dispatches per layer per step, byte-identical outputs fused on or off, and the same ONE compiled step signature. On CPU both settings lower to the identical XLA composition; under tp the fused layer loop also tiles row-parallel matmuls so each tile's all-reduce overlaps the next tile's compute.")
    d("use_fused_loss", bool, True, "Fuse the lm-head matmul with softmax cross-entropy at model training-loss sites (vocab-chunked, never materializes [B,S,V] logits; Pallas on TPU, lax.scan reference elsewhere). Models return (loss, None) on this path.")
    d("benchmark", bool, False, "Block on every op (sync dispatch) for timing.")
    d("log_memory_stats", bool, False, "Log live/peak device memory stats per allocation event.")
    d("allocator_strategy", str, "xla", "Allocator backing; on TPU the XLA/PJRT allocator owns HBM.")
    d("cudnn_deterministic", bool, False, "Deterministic op selection (maps to XLA determinism flags).")
    d("embedding_deterministic", int, 0, "Deterministic embedding grad accumulation level.")
    d("init_allocated_mem", bool, False, "Compat no-op: PJRT zero-initialises buffers.")
    d("max_inflight_ops", int, 256, "Async eager dispatch depth before forcing a sync.")
    d("flash_attn_version", int, 2, "Flash-attention algorithm family for the Pallas kernels.")
    d("dist_timeout_seconds", int, 1800, "Collective watchdog timeout (comm_task_manager parity).")
    d("tracer_mkldnn_ops_on", str, "", "Compat no-op on TPU.")
    d("use_stride_kernel", bool, False, "Compat: XLA owns layouts; stride kernels do not apply.")
    # observability layer (reference: the exported-flags + profiler surface,
    # SURVEY §5.1); registered here so env seeding works before the
    # paddle_tpu.observability import runs
    d("enable_metrics", bool, False, "Record runtime metrics (counters/gauges/histograms) into the global registry; off = every recording call is a no-op.")
    d("trace_sample_rate", float, 0.0, "Head-sampling probability (0..1) for per-request distributed tracing (observability.tracing). 0 disables tracing entirely — every trace call site then costs one cached-bool read.")
    d("trace_seed", int, 0, "Seed for the global tracer's id/sampling RNG: the same seed + request sequence reproduces the same sampling decisions and span ids.")
    d("trace_buffer_size", int, 4096, "Capacity of the tracer's bounded in-process span store (newest spans win); read when a Tracer is constructed.")
    d("flight_recorder_size", int, 1024, "Ring capacity of the always-on flight recorder: how many recent structured events the black box retains for postmortem dumps.")
    d("flight_recorder_dir", str, "", "Directory for automatic flight-recorder dumps (engine permanent failure, watchdog timeout, pump death); empty = the system temp dir.")
    d("metrics_port", int, 0, "Serve Prometheus text exposition on this localhost port via observability.start_metrics_server(); 0 disables the endpoint.")
    d("max_compiles_per_fn", int, 16, "Recompile-watchdog budget: warn when one traced function RE-compiles (compiles past its first_call traces) more than this many times; 0 disables the warning.")
    # fault-tolerance layer (registered here so env seeding works before the
    # paddle_tpu.testing.faults import runs; empty = injection fully off)
    d("fault_inject_plan", str, "", "Deterministic fault-injection plan: 'site:call_index:ExceptionName' entries joined by ';' (see testing/faults.py). Empty disables injection; fault sites then cost one cached-bool read.")
    # serving front end (paddle_tpu/serving/): same opt-in localhost pattern
    # as metrics_port — nothing listens unless asked
    d("serving_port", int, 0, "Serve the streaming generation HTTP endpoint (serving.start_serving_server) on this localhost port; 0 disables the endpoint.")
    # prefix-cache KV subsystem (inference/prefix_cache.py): content-hash
    # block dedup with copy-on-write over the paged pool; read at engine
    # construction (per-engine override via the enable_prefix_cache kwarg)
    d("enable_prefix_cache", bool, True, "Reference-counted content-hash KV block dedup for the continuous-batching engine: shared prompt prefixes are computed once and mapped copy-on-write into every request that repeats them; off = every prompt recomputes from token zero.")
    # hierarchical KV tier (inference/kv_tier.py): host-RAM spill tier under
    # the prefix cache; read at engine construction (per-engine override via
    # the kv_host_tier_bytes kwarg)
    d("kv_host_tier_bytes", int, 0, "Byte budget of the host-RAM KV spill tier under the prefix cache: LRU-evicted zero-reference chain blocks spill D2H into a bounded host pool instead of dying, and a prefix match against a spilled chain prefetches its blocks H2D asynchronously, overlapped with the chunked prefill of the uncached suffix. 0 (default) disables the tier — evicted chains are simply dropped, today's behavior. Greedy outputs are byte-identical with the tier on or off.")
    # speculative decoding (inference/spec_decode.py): n-gram self-speculation
    # riding the engine's one compiled mixed ragged step; read at engine
    # construction (per-engine override via the spec_decode kwarg)
    d("spec_decode", bool, False, "Self-speculative decoding on the continuous-batching engine: an n-gram prompt-lookup drafter proposes draft tokens per decode slot; drafts ride the SAME [max_slots, prefill_chunk] compiled step as prompt chunks (verification is data — zero new compiled signatures), accepted tokens commit in bulk, the first rejection rewinds the slot's block table. Greedy outputs are byte-identical on or off.")
    d("spec_decode_ngram", int, 3, "Longest n-gram of the request's prompt+generated history the speculative drafter matches (walks down to 1); read at engine construction.")
    d("spec_decode_tokens", int, 4, "Max draft tokens proposed per slot per step, capped at prefill_chunk - 1 so the draft plus the mandatory last-token row fit the engine's compiled chunk width.")
    # quantized serving (inference/engine.py + kernels/quant.py): int8 KV
    # blocks with in-kernel dequant, and weight-only int8 projections; both
    # read at engine construction — the compiled step signature stays ONE
    # either way (dtype changes the pool buffers, never the step shape)
    d("kv_cache_dtype", str, "bf16", "Storage dtype of the paged KV block pool: 'bf16' (default; byte-identical to the unquantized path) or 'int8' (symmetric per-token absmax quant applied inside the same fused append/CoW/prefetch writes; a per-block-per-head-per-slot fp32 scale table rides the pool through every lifecycle seam — refcounts, CoW, spill/prefetch, recovery, tp head-sharding — and dequant folds into the paged attention block walk, so no dequantized copy ever materializes). Halves KV HBM and host-tier bytes; greedy quality is gated by the bench quality-delta record.")
    d("weight_only_int8", bool, False, "Weight-only int8 for the lm-head and MLP projections (inference-only): matching nn.Linear weights are quantized once host-side with per-output-channel scales, the scales ride the compiled step as extra trailing params (signature stays fixed), and matmuls dispatch to the Pallas int8xbf16 dot kernel (kernels/quant.py) with an XLA dequant-matmul fallback in numeric lockstep.")
    # tensor-parallel serving (distributed/tp.py): shard the engine's one
    # compiled step over a ['tp'] device mesh; read at engine construction
    # (per-engine override via the tp kwarg)
    d("engine_tp_degree", int, 1, "Tensor-parallel degree of the continuous-batching engine: attention heads and the paged KV block pool partition per device along a single-axis ['tp'] mesh, MLP splits Megatron-style (one all-reduce per layer), the lm-head shards over vocab. 1 = single-chip engine (byte-identical to the unsharded path). Must divide the model's KV heads; needs that many visible devices.")
    # fleet observability (observability/slo.py + aggregate.py): the SLO
    # burn-rate monitor riding the cluster router's probe loop, and the
    # coordinated incident snapshots it (and the death seams) write. Read
    # when an SLOConfig / ClusterObserver is constructed, never per tick.
    d("slo_ttft_p99_target_s", float, 1.0, "SLO target for the cluster-level TTFT p99 (seconds): the burn-rate monitor's ttft signal is the observed windowed p99 divided by this.")
    d("slo_goodput_target", float, 0.9, "SLO target fraction of terminals that finish ok INSIDE their deadline; the monitor's slo-violation burn rate is the windowed violation fraction divided by the remaining error budget (1 - target).")
    d("slo_shed_budget", float, 0.1, "Error budget for the shed rate: fraction of terminals allowed to end in any non-ok outcome before the shed burn rate reads 1.0.")
    d("slo_failover_budget", float, 0.1, "Error budget for the failover rate: re-dispatch attempts per routing dispatch allowed before the failover burn rate reads 1.0.")
    d("slo_fast_window_s", float, 5.0, "Fast burn-rate window (seconds). A state escalates only when BOTH the fast and slow windows burn past a threshold — the fast window catches the onset, the slow window proves it is sustained.")
    d("slo_slow_window_s", float, 60.0, "Slow burn-rate window (seconds); see slo_fast_window_s.")
    d("slo_warn_burn", float, 1.0, "Burn-rate threshold that latches WARN (hysteresis: releases at half this value). Burn 1.0 = consuming the error budget exactly as fast as allowed.")
    d("slo_page_burn", float, 4.0, "Burn-rate threshold that latches PAGE (hysteresis: releases at half this value); entering PAGE writes a coordinated incident snapshot.")
    d("slo_min_terminals", int, 8, "Minimum terminals inside a window before its budget-based burn rates are trusted (the ttft signal is exempt); prevents paging on the first failed request of a quiet cluster.")
    d("incident_dir", str, "", "Directory for coordinated cluster incident snapshots (observability/aggregate.py): one sub-directory per incident with every replica's flight ring, the router's routing log, sampled spans and the cluster health view. Empty = flight_recorder_dir, else the system temp dir.")
    d("incident_cooldown_s", float, 30.0, "Minimum seconds between two incident snapshots for the SAME reason (a flapping replica must not fill the disk with identical postmortems).")
    # device-time attribution (observability/devprof.py): per-step cost
    # profiles, host-bubble decomposition, measured comm share
    d("devprof_sample_rate", float, 0.0, "Fraction of engine steps profiled by the device-time attribution layer (observability/devprof.py): a sampled step is timed device-sync-honest, decomposed into host-prep / dispatch-gap / device segments, and its device time apportioned across attention/matmul/collective/other using the compile-time cost profile as the attribution prior. 0 (default) disables profiling entirely — every step then costs one cached-bool read — and deterministic stride sampling (no RNG draw) picks steps at partial rates. Rate > 0 also arms compile-time cost-profile capture (an introspective AOT lowering per compiled signature, paid once per compile).")
    d("devprof_timeline_size", int, 256, "Capacity of each engine's bounded step-timeline ring (devprof): how many recent sampled step profiles are retained for /healthz, incident snapshots and the dump CLI; newest win.")


_define_builtin_flags()


def set_flags(flags: Dict[str, Any]) -> None:
    """Set one or more global flags. Mirrors ``paddle.set_flags``."""
    for k, v in flags.items():
        GLOBAL_FLAGS.set(k.removeprefix("FLAGS_"), v)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    """Read one, several, or all global flags. Mirrors ``paddle.get_flags``."""
    if flags is None:
        names: Iterable[str] = GLOBAL_FLAGS.names()
    elif isinstance(flags, str):
        names = [flags]
    else:
        names = flags
    return {n: GLOBAL_FLAGS.get(n.removeprefix("FLAGS_")) for n in names}


def define_flag(name: str, type_: type, default: Any, help_: str = "") -> None:
    """Register a new flag (used by subsystems at import time)."""
    GLOBAL_FLAGS.define(name, type_, default, help_)
