"""``paddle_tpu.fft`` — discrete Fourier transforms.

Reference: ``python/paddle/fft.py`` (fft/ifft/rfft/... over the fft_c2c /
fft_r2c / fft_c2r kernels). TPU-native: every transform is one dispatched op
over ``jnp.fft`` — XLA lowers FFTs natively (DUCC on CPU, dedicated HLO on
TPU) and jax supplies the complex-valued VJPs, so all transforms are
differentiable on the eager tape.

``norm`` accepts paddle's {"backward", "ortho", "forward"} (numpy-compatible).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm: Optional[str]) -> Optional[str]:
    if norm in (None, "backward"):
        return None  # numpy default
    if norm in ("ortho", "forward"):
        return norm
    raise ValueError(f"norm must be 'backward'/'ortho'/'forward', got {norm!r}")


def _mk1d(name: str, fn: Any):
    def op(x: Any, n: Optional[int] = None, axis: int = -1, norm: str = "backward", name: Any = None) -> Tensor:
        nm = _norm(norm)
        return call_op(name, lambda a: fn(a, n=n, axis=axis, norm=nm), x)

    op.__name__ = name
    op.__doc__ = f"``paddle.fft.{name}`` (reference fft.py; XLA-native FFT)."
    return op


def _mk2d(name: str, fn: Any):
    def op(x: Any, s: Optional[Sequence[int]] = None, axes: Sequence[int] = (-2, -1), norm: str = "backward", name: Any = None) -> Tensor:
        nm = _norm(norm)
        return call_op(name, lambda a: fn(a, s=s, axes=tuple(axes), norm=nm), x)

    op.__name__ = name
    op.__doc__ = f"``paddle.fft.{name}`` (reference fft.py; XLA-native FFT)."
    return op


def _mkn(name: str, fn: Any):
    def op(x: Any, s: Optional[Sequence[int]] = None, axes: Optional[Sequence[int]] = None, norm: str = "backward", name: Any = None) -> Tensor:
        nm = _norm(norm)
        ax = None if axes is None else tuple(axes)
        return call_op(name, lambda a: fn(a, s=s, axes=ax, norm=nm), x)

    op.__name__ = name
    op.__doc__ = f"``paddle.fft.{name}`` (reference fft.py; XLA-native FFT)."
    return op


fft = _mk1d("fft", jnp.fft.fft)
ifft = _mk1d("ifft", jnp.fft.ifft)
rfft = _mk1d("rfft", jnp.fft.rfft)
irfft = _mk1d("irfft", jnp.fft.irfft)
hfft = _mk1d("hfft", jnp.fft.hfft)
ihfft = _mk1d("ihfft", jnp.fft.ihfft)

fft2 = _mk2d("fft2", jnp.fft.fft2)
ifft2 = _mk2d("ifft2", jnp.fft.ifft2)
rfft2 = _mk2d("rfft2", jnp.fft.rfft2)
irfft2 = _mk2d("irfft2", jnp.fft.irfft2)


def hfft2(x: Any, s: Optional[Sequence[int]] = None, axes: Sequence[int] = (-2, -1), norm: str = "backward", name: Any = None) -> Tensor:
    nm = _norm(norm)
    return call_op("hfft2", lambda a: _hfftn_impl(a, s, tuple(axes), nm), x)


def ihfft2(x: Any, s: Optional[Sequence[int]] = None, axes: Sequence[int] = (-2, -1), norm: str = "backward", name: Any = None) -> Tensor:
    nm = _norm(norm)
    return call_op("ihfft2", lambda a: _ihfftn_impl(a, s, tuple(axes), nm), x)


fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


def _hfftn_impl(a, s, axes, norm):
    # hermitian N-D = c2c over the leading axes + c2r (hfft) over the last
    # (scipy.fft.hfftn decomposition; jnp has no hfftn primitive)
    if axes is None:
        axes = tuple(range(a.ndim))
    axes = tuple(axes)
    if s is None:
        # hfft's default output length convention: 2*(n_in-1) on the c2r axis
        s = [a.shape[ax] for ax in axes]
        s[-1] = 2 * (a.shape[axes[-1]] - 1)
    else:
        s = list(s)  # user-supplied sizes are honored verbatim
    if len(axes) > 1:
        a = jnp.fft.fftn(a, s=s[:-1], axes=axes[:-1], norm=norm)
    return jnp.fft.hfft(a, n=s[-1], axis=axes[-1], norm=norm)


def _ihfftn_impl(a, s, axes, norm):
    if axes is None:
        axes = tuple(range(a.ndim))
    axes = tuple(axes)
    s = list(s) if s is not None else [a.shape[ax] for ax in axes]
    out = jnp.fft.ihfft(a, n=s[-1], axis=axes[-1], norm=norm)
    if len(axes) > 1:
        out = jnp.fft.ifftn(out, s=s[:-1], axes=axes[:-1], norm=norm)
    return out


def hfftn(x: Any, s: Optional[Sequence[int]] = None, axes: Optional[Sequence[int]] = None, norm: str = "backward", name: Any = None) -> Tensor:
    nm = _norm(norm)
    return call_op("hfftn", lambda a: _hfftn_impl(a, s, axes, nm), x)


def ihfftn(x: Any, s: Optional[Sequence[int]] = None, axes: Optional[Sequence[int]] = None, norm: str = "backward", name: Any = None) -> Tensor:
    nm = _norm(norm)
    return call_op("ihfftn", lambda a: _ihfftn_impl(a, s, axes, nm), x)


def fftfreq(n: int, d: float = 1.0, dtype: Any = None, name: Any = None) -> Tensor:
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from paddle_tpu.core.dtypes import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n: int, d: float = 1.0, dtype: Any = None, name: Any = None) -> Tensor:
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from paddle_tpu.core.dtypes import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def fftshift(x: Any, axes: Optional[Sequence[int]] = None, name: Any = None) -> Tensor:
    ax = None if axes is None else tuple(axes) if not isinstance(axes, int) else axes
    return call_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=ax), x)


def ifftshift(x: Any, axes: Optional[Sequence[int]] = None, name: Any = None) -> Tensor:
    ax = None if axes is None else tuple(axes) if not isinstance(axes, int) else axes
    return call_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=ax), x)
