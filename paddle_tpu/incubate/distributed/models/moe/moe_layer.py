"""MoELayer: expert-parallel mixture of experts.

Reference: ``python/paddle/incubate/distributed/models/moe/moe_layer.py:263``
— its flow is gate → per-rank index build → ``global_scatter`` all-to-all →
local experts → ``global_gather``. TPU-native flow (GShard einsum form):

    dispatch:  [T,E,C] one-hot × [T,M] tokens  → [E,C,M]
    experts:   batched over the (sharded) E axis → [E,C,M]
    combine:   [T,E,C] weights × [E,C,M]        → [T,M]

When the expert axis is sharded over an 'ep' mesh dimension, XLA lowers the
dispatch/combine einsums to exactly the all-to-all the reference hand-codes —
and fuses the capacity masking into them. Experts with stacked parameters
(``Experts``) ride the same sharding; a python list of per-expert Layers is
also accepted (compat path, runs experts sequentially).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.distributed.models.moe.gate import (
    BaseGate,
    GShardGate,
    NaiveGate,
    SwitchGate,
)
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["MoELayer", "Experts"]


class Experts(Layer):
    """E experts with stacked FFN parameters ``[E, ...]`` — batched expert
    compute on the MXU; the E axis carries the 'ep' sharding."""

    def __init__(
        self,
        num_experts: int,
        d_model: int,
        d_hidden: int,
        activation: str = "gelu",
    ) -> None:
        super().__init__()
        self.num_experts = num_experts
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.activation = activation
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)

    def shard_over(self, mesh: Any, axis: str = "ep") -> None:
        """Place the expert dim over the mesh's ep axis (Shard(0))."""
        from paddle_tpu.distributed.api import shard_layer, shard_tensor
        from paddle_tpu.distributed.placements import Replicate, Shard

        plc = [Shard(0) if n == axis else Replicate() for n in mesh.dim_names]

        def shard_fn(name: str, sublayer: Any, m: Any) -> None:
            for p in sublayer._parameters.values():
                if p is None:
                    continue
                d = shard_tensor(p, m, plc)
                p._data = d._data
                p.process_mesh = m
                p.placements = plc

        shard_layer(self, mesh, shard_fn)

    def forward(self, dispatched: Any) -> Any:  # [E, C, M]
        h = paddle_matmul(dispatched, self.w1) + self.b1
        h = F.gelu(h) if self.activation == "gelu" else F.relu(h)
        return paddle_matmul(h, self.w2) + self.b2


def paddle_matmul(a: Any, b: Any) -> Any:
    import paddle_tpu

    return paddle_tpu.matmul(a, b)


class MoELayer(Layer):
    """Reference-parity constructor: ``MoELayer(d_model, experts, gate=...,
    moe_group=..., recompute_interval=...)``; ``gate`` may be a config dict
    (``{"type": "gshard", "top_k": 2}``), a gate name, or a BaseGate."""

    def __init__(
        self,
        d_model: int,
        experts: Union[Experts, Sequence[Layer], None] = None,
        gate: Union[BaseGate, dict, str, None] = None,
        moe_group: Any = None,
        mp_group: Any = None,
        recompute_interval: int = 0,
        recompute_ctx: Any = None,
        num_experts: Optional[int] = None,
        top_k: int = 2,
        capacity_factor: float = 1.2,
        ep_axis: str = "ep",
    ) -> None:
        super().__init__()
        self.d_model = d_model
        if experts is None:
            raise ValueError("MoELayer needs experts (an Experts module or list of Layers)")
        if isinstance(experts, Experts):
            self.experts = experts
            self.num_experts = experts.num_experts
        else:
            self.experts_list = list(experts)
            for i, ex in enumerate(self.experts_list):
                self.add_sublayer(f"expert_{i}", ex)
            self.experts = None
            self.num_experts = len(self.experts_list)
        if num_experts is not None and num_experts != self.num_experts:
            raise ValueError(f"num_experts={num_experts} != len(experts)={self.num_experts}")

        if isinstance(gate, BaseGate):
            self.gate = gate
        else:
            if isinstance(gate, dict):
                gtype = gate.get("type", "gshard")
                top_k = gate.get("top_k", top_k)
            else:
                gtype = gate or "gshard"
            cls = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}[gtype]
            self.gate = cls(d_model, self.num_experts, top_k=top_k)
        self.top_k = self.gate.top_k
        self.capacity_factor = capacity_factor
        self.recompute_interval = recompute_interval
        self._ep_axis = ep_axis
        self._mesh = None
        self._moe_group_mesh = moe_group if hasattr(moe_group, "dim_names") else None
        self._resolve_mesh()

    def _resolve_mesh(self) -> None:
        """Bind the EP mesh — at construction if one is already set, else
        lazily on first forward (supports build-then-set_mesh ordering and an
        explicit moe_group=ProcessMesh)."""
        if self._mesh is not None:
            return
        from paddle_tpu.distributed.mesh import get_mesh

        mesh = self._moe_group_mesh or get_mesh()
        if mesh is not None and self._ep_axis in mesh.dim_names and mesh.get_dim_size(self._ep_axis) > 1:
            self._mesh = mesh
            if isinstance(self.experts, Experts):
                self.experts.shard_over(mesh, self._ep_axis)

    # aux loss for the trainer (reference: gate.get_loss aggregated by caller)
    def get_aux_loss(self, clear: bool = True) -> Optional[Tensor]:
        return self.gate.get_loss(clear)

    def _constrain_ep(self, t: Tensor) -> Tensor:
        """Shard the leading expert dim over ep — the all-to-all point."""
        if self._mesh is None:
            return t
        from paddle_tpu.distributed.api import shard_tensor
        from paddle_tpu.distributed.placements import Replicate, Shard

        plc = [Shard(0) if n == self._ep_axis else Replicate() for n in self._mesh.dim_names]
        return shard_tensor(t, self._mesh, plc, stop_gradient=t.stop_gradient)

    def _run_experts(self, dispatched: Any) -> Any:
        import paddle_tpu

        if self.experts is not None:
            return self.experts(dispatched)
        outs = [ex(dispatched[e]) for e, ex in enumerate(self.experts_list)]
        return paddle_tpu.stack(outs, axis=0)

    def forward(self, x: Any) -> Any:
        self._resolve_mesh()
        orig_shape = list(x.shape)
        m = orig_shape[-1]
        xt = x.reshape([-1, m])  # [T, M]
        combine, dispatch, cap = self.gate(xt, self.capacity_factor)

        import paddle_tpu

        # dispatch: [T,E,C] × [T,M] → [E,C,M]
        dispatched = paddle_tpu.einsum("tec,tm->ecm", dispatch.astype(xt.dtype), xt)
        dispatched = self._constrain_ep(dispatched)
        if self.recompute_interval > 0:
            from paddle_tpu.distributed.fleet.recompute import recompute

            expert_out = recompute(self._run_experts, dispatched)
        else:
            expert_out = self._run_experts(dispatched)
        expert_out = self._constrain_ep(expert_out)
        # combine: [T,E,C] × [E,C,M] → [T,M]
        out = paddle_tpu.einsum("tec,ecm->tm", combine.astype(xt.dtype), expert_out)
        return out.reshape(orig_shape)
