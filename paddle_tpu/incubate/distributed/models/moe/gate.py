"""MoE gates: naive (top-k, no aux loss), GShard (top-2 + load-balance loss +
capacity), Switch (top-1 + load-balance loss + capacity).

Reference: ``python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py``. TPU-native: instead of producing per-rank
index lists for ``global_scatter``, each gate produces dense one-hot
**dispatch/combine tensors** (the GShard einsum formulation) — the layout
GSPMD turns into the expert all-to-all when the expert axis is sharded.

Shapes: input ``[T, M]`` tokens; outputs
``combine_weights [T, E, C]`` (float), ``dispatch_mask [T, E, C]`` (bool),
``aux_loss`` (scalar or None).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.common import Linear
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, top_k: int) -> int:
    # ceiling, not floor (GShard): at factor 1.0 a perfectly balanced router
    # must not drop tokens
    cap = -(-int(capacity_factor * top_k * num_tokens) // num_experts)
    return max(cap, top_k)


def _topk_dispatch(logits, top_k: int, capacity: int, jitter_key=None, renormalize: bool = True):
    """Shared top-k → capacity-limited one-hot dispatch (raw jax arrays).

    Returns (combine [T,E,C], dispatch [T,E,C] bool, gates [T,E], top1_mask
    [T,E]) — the last two feed the load-balance aux loss."""
    t, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    _, expert_idx = jax.lax.top_k(gates, top_k)  # [T, K]

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), bool)
    # running per-expert fill count decides each token's slot, priority by
    # token order (matches the reference's prune_gate_by_capacity semantics)
    fill = jnp.zeros((e,), jnp.int32)
    top1_mask = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)

    gate_vals = jnp.take_along_axis(gates, expert_idx, axis=1)  # [T, K]
    if renormalize and top_k > 1:
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    for k in range(top_k):
        sel = jax.nn.one_hot(expert_idx[:, k], e, dtype=jnp.int32)  # [T, E]
        pos = fill[None, :] + jnp.cumsum(sel, axis=0) - sel  # slot if selected
        within = (pos < capacity) & (sel > 0)
        slot = jnp.clip(pos, 0, capacity - 1)
        onehot_slot = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # [T, E, C]
        place = onehot_slot * within[..., None]
        combine = combine + place * gate_vals[:, k, None, None]
        dispatch = dispatch | (place > 0)
        fill = fill + sel.sum(axis=0)
    return combine, dispatch, gates, top1_mask


class BaseGate(Layer):
    """Gate base (reference ``gate/base_gate.py``): owns the routing linear."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1, top_k: int = 2) -> None:
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert * world_size  # total experts
        self.top_k = top_k
        self.wg = Linear(d_model, self.num_expert, bias_attr=False)
        self._loss: Optional[Any] = None

    def set_loss(self, loss: Any) -> None:
        self._loss = loss

    def get_loss(self, clear: bool = True) -> Optional[Any]:
        loss = self._loss
        if clear:
            self._loss = None
        return loss

    def _dispatch(self, x: Any, capacity_factor: float, aux: str, jitter_eps: float = 0.0):
        from paddle_tpu.core.dispatch import call_op

        logits = self.wg(x)  # [T, E]
        if jitter_eps > 0.0 and self.training:
            # reference switch_gate.py: multiplicative uniform(1±eps) routing
            # noise during training breaks early expert-collapse symmetry
            import paddle_tpu.core.rng as _rng

            jkey = _rng.next_key()
            logits = call_op(
                "moe_gate_jitter",
                lambda lg, kk: lg
                * jax.random.uniform(
                    kk, lg.shape, jnp.float32, 1.0 - jitter_eps, 1.0 + jitter_eps
                ),
                logits,
                jkey,
            )
        t = x.shape[0]
        cap = _capacity(t, self.num_expert, capacity_factor, self.top_k)
        top_k = self.top_k

        def _impl(lg):
            combine, dispatch, gates, top1 = _topk_dispatch(lg, top_k, cap)
            if aux == "none":
                loss = jnp.zeros((), jnp.float32)
            else:
                # load-balance loss: E * Σ_e mean-prob_e * mean-top1-frac_e
                me = gates.mean(axis=0)
                ce = top1.mean(axis=0)
                loss = (me * ce).sum() * float(gates.shape[1])
            return combine, dispatch.astype(jnp.float32), loss

        combine, dispatch, loss = call_op("moe_gate", _impl, logits)
        self.set_loss(loss)
        return combine, dispatch, cap


class NaiveGate(BaseGate):
    """Top-k gate without load balancing (reference ``naive_gate.py``)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1, top_k: int = 2) -> None:
        super().__init__(d_model, num_expert, world_size, top_k)

    def forward(self, x: Any, capacity_factor: float = 1.0):
        return self._dispatch(x, capacity_factor, aux="none")


class GShardGate(BaseGate):
    """Top-2 gate with load-balance aux loss + capacity
    (reference ``gshard_gate.py``)."""

    def __init__(
        self,
        d_model: int,
        num_expert: int,
        world_size: int = 1,
        top_k: int = 2,
        capacity: Tuple[float, float] = (1.2, 2.4),
        group: Any = None,
    ) -> None:
        super().__init__(d_model, num_expert, world_size, top_k=top_k)
        self.capacity_factor_train, self.capacity_factor_eval = capacity

    def forward(self, x: Any, capacity_factor: Optional[float] = None):
        default = self.capacity_factor_train if self.training else self.capacity_factor_eval
        return self._dispatch(x, capacity_factor or default, aux="load_balance")


class SwitchGate(BaseGate):
    """Top-1 switch-transformer gate (reference ``switch_gate.py``)."""

    def __init__(
        self,
        d_model: int,
        num_expert: int,
        world_size: int = 1,
        top_k: int = 1,
        switch_eps: float = 0.1,
        capacity: Tuple[float, float] = (1.2, 2.4),
        group: Any = None,
    ) -> None:
        super().__init__(d_model, num_expert, world_size, top_k=1)
        self.switch_eps = switch_eps
        self.capacity_factor_train, self.capacity_factor_eval = capacity

    def forward(self, x: Any, capacity_factor: Optional[float] = None):
        default = self.capacity_factor_train if self.training else self.capacity_factor_eval
        return self._dispatch(
            x, capacity_factor or default, aux="load_balance", jitter_eps=self.switch_eps
        )
