"""Mixture-of-Experts with expert parallelism.

Reference: ``python/paddle/incubate/distributed/models/moe/``
(``moe_layer.py:263 MoELayer``, gates ``gate/{naive,gshard,switch}_gate.py``,
expert-parallel all-to-all via ``global_scatter``/``global_gather`` ops).
"""

from paddle_tpu.incubate.distributed.models.moe.gate import (  # noqa: F401
    BaseGate,
    GShardGate,
    NaiveGate,
    SwitchGate,
)
from paddle_tpu.incubate.distributed.models.moe.moe_layer import (  # noqa: F401
    Experts,
    MoELayer,
)
