"""``paddle_tpu.incubate`` (reference ``python/paddle/incubate``): fused-op
functional surface. On TPU "fused" means the XLA/Pallas-fused composition —
the API parity matters, the fusion is the compiler's job."""

from paddle_tpu.incubate import distributed  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401,E402
