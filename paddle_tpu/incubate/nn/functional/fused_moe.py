"""Fused dropless MoE over ``lax.ragged_dot``.

Reference: the fused MoE kernel family
(``paddle/phi/kernels/fusion/gpu/fused_moe_kernel.cu``, exposed as
``paddle.incubate.nn.functional.fused_moe``): gate → top-k → grouped expert
GEMMs → weighted combine, with no [E, C, M] capacity buffer.

TPU-native mechanics: tokens are sorted by expert id and the two expert FFN
GEMMs run as ``jax.lax.ragged_dot`` — the Mosaic grouped-matmul primitive
that keeps the MXU busy across experts of unequal load. Dropless: every
token reaches its experts (group sizes are data-dependent, shapes stay
static at T*K). The gather/sort/scatter bookkeeping is XLA-fused around the
two ragged GEMMs.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["fused_moe"]


def _fused_moe_impl(
    x: jnp.ndarray,  # [T, M]
    gate_w: jnp.ndarray,  # [M, E]
    ffn1_w: jnp.ndarray,  # [E, M, H] (or [E, M, 2H] for swiglu)
    ffn2_w: jnp.ndarray,  # [E, H, M]
    top_k: int,
    norm_topk_prob: bool,
    activation: str,
) -> jnp.ndarray:
    t, m = x.shape
    e = gate_w.shape[1]
    logits = (x.astype(jnp.float32) @ gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, top_k)  # [T, K]
    if norm_topk_prob:
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)

    flat_expert = topi.reshape(-1)  # [T*K]
    flat_token = jnp.repeat(jnp.arange(t, dtype=jnp.int32), top_k)
    flat_weight = topv.reshape(-1)

    order = jnp.argsort(flat_expert)  # stable grouping by expert
    tok_sorted = flat_token[order]
    w_sorted = flat_weight[order]
    gathered = x[tok_sorted]  # [T*K, M]
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    h = jax.lax.ragged_dot(gathered, ffn1_w.astype(x.dtype), group_sizes)
    if activation == "swiglu":
        half = h.shape[-1] // 2
        h = jax.nn.silu(h[:, :half]) * h[:, half:]
    elif activation == "gelu":
        h = jax.nn.gelu(h, approximate=False)  # erf-exact, paddle default
    elif activation == "relu":
        h = jax.nn.relu(h)
    else:
        raise ValueError(f"unsupported activation {activation!r}")
    out = jax.lax.ragged_dot(h, ffn2_w.astype(x.dtype), group_sizes)  # [T*K, M]

    out = out * w_sorted[:, None].astype(out.dtype)
    y = jnp.zeros((t, m), out.dtype).at[tok_sorted].add(out)
    return y


def fused_moe(
    x: Any,
    gate_weight: Any,
    ffn1_weight: Any,
    ffn2_weight: Any,
    moe_topk: int = 2,
    norm_topk_prob: bool = True,
    activation: str = "swiglu",
) -> Tensor:
    """Dropless fused MoE (reference ``fused_moe``): tokens ``[T, M]`` or
    ``[B, S, M]``; ``ffn1_weight [E, M, H or 2H]``, ``ffn2_weight [E, H, M]``.
    Differentiable through the eager tape."""
    xt = x if isinstance(x, Tensor) else Tensor(x)
    lead = None
    if len(xt.shape) == 3:
        lead = tuple(xt.shape[:2])
        xt = xt.reshape([lead[0] * lead[1], xt.shape[-1]])

    def fn(xa, gw, w1, w2):
        return _fused_moe_impl(
            xa, gw, w1, w2, int(moe_topk), bool(norm_topk_prob), activation
        )

    out = call_op("fused_moe", fn, xt, gate_weight, ffn1_weight, ffn2_weight)
    if lead is not None:
        out = out.reshape([lead[0], lead[1], out.shape[-1]])
    return out
