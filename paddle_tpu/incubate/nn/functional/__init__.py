"""Fused functional ops (reference ``python/paddle/incubate/nn/functional/``:
fused_rms_norm, swiglu, fused_rotary_position_embedding, fused_bias_act, …).

Each maps to a composition that XLA fuses on TPU (or a Pallas kernel where
profiling says XLA's fusion is insufficient — see ``paddle_tpu.kernels``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import defop
from paddle_tpu.nn.functional.activation import swiglu  # noqa: F401
from paddle_tpu.nn.functional.common import rms_norm

__all__ = [
    "fused_rms_norm",
    "fused_layer_norm",
    "swiglu",
    "fused_rotary_position_embedding",
    "fused_bias_act",
    "fused_linear",
    "fused_bias_dropout_residual_layer_norm",
    "fused_dropout_add",
    "masked_multihead_attention",
    "block_multihead_attention",
    "block_cache_prefill",
    "block_cache_append",
    "BlockKVCache",
    "fused_moe",
]

from paddle_tpu.incubate.nn.functional.block_attention import (  # noqa: E402,F401
    BlockKVCache,
    block_cache_append,
    block_cache_prefill,
    block_multihead_attention,
)
from paddle_tpu.incubate.nn.functional.fused_moe import fused_moe  # noqa: E402,F401


def fused_rms_norm(
    x: Any,
    norm_weight: Any,
    norm_bias: Any = None,
    epsilon: float = 1e-6,
    begin_norm_axis: int = -1,
    bias: Any = None,
    residual: Any = None,
    quant_scale: float = -1,
    **kwargs: Any,
) -> Tuple[Any, ...]:
    """Reference ``fused_rms_norm`` (rms_norm kernel + optional bias/residual
    add). Returns (out, residual_out) like the reference when residual given."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(
    x: Any,
    norm_weight: Any,
    norm_bias: Any = None,
    epsilon: float = 1e-5,
    begin_norm_axis: int = -1,
    bias: Any = None,
    residual: Any = None,
    **kwargs: Any,
) -> Any:
    from paddle_tpu.nn.functional.common import layer_norm

    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = layer_norm(x, None, norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, residual_out
    return out


@defop("fused_rotary_position_embedding", tensor_method=None)
def _fused_rope_op(q, k, v, sin, cos, use_neox_rotary_style=True):
    """RoPE (reference ``fused_ops.yaml:408`` fused_rotary_position_embedding;
    kernel ``paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu``).
    Layout [B, S, H, D]; sin/cos [1, S, 1, D] (or [S, D])."""

    def rope(x):
        if x is None:
            return None
        # per-batch tables (leading dim > 1, decode with ragged positions)
        # cannot collapse to the kernel's [S, D] layout — XLA path only
        if (
            use_neox_rotary_style
            and x.shape[-1] % 128 == 0
            and (cos.ndim == 2 or cos.shape[0] == 1)
        ):
            from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

            if pallas_enabled("use_pallas_fused"):
                try:
                    from paddle_tpu.kernels.fused import fused_rope_pallas

                    c2 = cos if cos.ndim == 2 else cos.reshape(cos.shape[1], cos.shape[-1])
                    s2 = sin if sin.ndim == 2 else sin.reshape(sin.shape[1], sin.shape[-1])
                    return fused_rope_pallas(x, c2, s2)
                except Exception as exc:  # pragma: no cover - TPU-only path
                    warn_fallback("fused_rope", exc)
        s = sin
        c = cos
        if s.ndim == 2:
            s = s[None, :, None, :]
            c = c[None, :, None, :]
        s = s.astype(x.dtype)
        c = c.astype(x.dtype)
        if use_neox_rotary_style:
            half = x.shape[-1] // 2
            x1, x2 = x[..., :half], x[..., half:]
            rotated = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x[..., 0::2]
            x2 = x[..., 1::2]
            rotated = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        return x * c + rotated * s

    return tuple(rope(t) for t in (q, k, v) if t is not None)


def fused_rotary_position_embedding(
    q: Any,
    k: Any = None,
    v: Any = None,
    sin: Any = None,
    cos: Any = None,
    position_ids: Any = None,
    use_neox_rotary_style: bool = True,
    time_major: bool = False,
    rotary_emb_base: float = 10000.0,
) -> Tuple[Any, ...]:
    if sin is None or cos is None:
        # build sin/cos table from base
        b, s, h, d = q.shape
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        from paddle_tpu.core.tensor import Tensor

        sin = Tensor(jnp.sin(emb))
        cos = Tensor(jnp.cos(emb))
    outs = _fused_rope_op(q, k, v, sin, cos, use_neox_rotary_style=use_neox_rotary_style)
    result = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    while len(result) < 3:
        result.append(None)
    return tuple(result[:3])


@defop("fused_bias_act", tensor_method=None)
def fused_bias_act(x, bias=None, act_method="gelu", dequant_scales=None, shift=None, smooth=None, **kwargs):
    """Reference ``fused_ops.yaml:201`` fused_bias_act."""
    if bias is not None:
        x = x + bias
    if act_method in ("gelu",):
        return jax.nn.gelu(x)
    if act_method in ("relu",):
        return jax.nn.relu(x)
    if act_method in ("swiglu", "silu"):
        if act_method == "swiglu":
            a, b = jnp.split(x, 2, axis=-1)
            return jax.nn.silu(a) * b
        return jax.nn.silu(x)
    raise ValueError(f"unsupported act_method {act_method}")


@defop("fused_linear", tensor_method=None)
def fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    out = jnp.matmul(x, w)
    if bias is not None:
        out = out + bias
    return out


@defop("masked_multihead_attention", tensor_method=None)
def masked_multihead_attention(q, k, v, cache_k, cache_v, seq_len, scale=None):
    """Decode-phase attention with append-to-cache — the static-shape KV-cache
    attention step (reference ``paddle/phi/ops/yaml/ops.yaml:3074``
    ``masked_multihead_attention_``, CUDA kernel
    ``paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu``).

    One new token per sequence attends to every cached position up to its
    current length; the new K/V are written into fixed-size buffers with
    ``dynamic_update_slice`` so every decode step is the SAME compiled XLA
    program (no shape growth, no recompiles — the TPU analog of the
    reference's in-place `_` op).

    Args:
      q/k/v: ``[B, 1, H, D]`` / ``[B, 1, HK, D]`` this step's post-RoPE
        projections (GQA: HK may divide H).
      cache_k/cache_v: ``[B, S_max, HK, D]`` static cache buffers.
      seq_len: int32 scalar or ``[B]`` — tokens already cached; the new token
        is written at this index.
      scale: attention scale, default ``1/sqrt(D)``.

    Returns ``(out [B, 1, H, D], cache_k', cache_v')``.
    """
    b, _, h, d = q.shape
    hk = cache_k.shape[2]
    s_max = cache_k.shape[1]
    group = h // hk
    if scale is None:
        scale = 1.0 / (d**0.5)
    lens = jnp.broadcast_to(jnp.asarray(seq_len, jnp.int32).reshape(-1), (b,))
    try:
        # concrete lengths (eager decode loops): fail loudly on overflow —
        # inside jit the write index would silently clamp onto the last slot
        concrete = np.asarray(lens)
        if (concrete >= s_max).any():
            raise ValueError(
                f"KV cache overflow: seq_len {concrete.max()} >= buffer size {s_max}"
            )
    except (jax.errors.TracerArrayConversionError, jax.errors.ConcretizationTypeError):
        pass

    def append(buf, new, ln):
        # buf [S_max, HK, D], new [1, HK, D]
        return jax.lax.dynamic_update_slice(buf, new.astype(buf.dtype), (ln, 0, 0))

    ck = jax.vmap(append)(cache_k, k, lens)
    cv = jax.vmap(append)(cache_v, v, lens)

    qg = q.reshape(b, 1, hk, group, d).astype(jnp.float32)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, ck.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max, dtype=jnp.int32)
    allowed = pos[None, :] <= lens[:, None]  # include the just-written token
    logits = jnp.where(allowed[:, None, None, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p, cv.astype(jnp.float32))
    return out.reshape(b, 1, h, d).astype(q.dtype), ck, cv


def fused_bias_dropout_residual_layer_norm(
    x: Any,
    residual: Any,
    bias: Any = None,
    ln_scale: Any = None,
    ln_bias: Any = None,
    dropout_rate: float = 0.0,
    ln_epsilon: float = 1e-5,
    training: bool = True,
    mode: str = "upscale_in_train",
) -> Any:
    from paddle_tpu.nn.functional.common import dropout, layer_norm

    if bias is not None:
        x = x + bias
    x = dropout(x, p=dropout_rate, training=training, mode=mode)
    x = x + residual
    return layer_norm(x, None, ln_scale, ln_bias, ln_epsilon)


def fused_dropout_add(x: Any, y: Any, p: float = 0.5, training: bool = True, mode: str = "upscale_in_train") -> Any:
    from paddle_tpu.nn.functional.common import dropout

    return dropout(x, p=p, training=training, mode=mode) + y


def fused_softmax_mask(x: Any, mask: Any) -> Any:
    """Reference ``fused_softmax_mask kernel``: softmax(x + mask) in one
    fused step (XLA fuses the add into the softmax)."""
    from paddle_tpu.core.dispatch import call_op

    def _impl(x, m):
        return jax.nn.softmax(x.astype(jnp.float32) + m.astype(jnp.float32), axis=-1).astype(x.dtype)

    return call_op("fused_softmax_mask", _impl, x, mask)


def fused_softmax_mask_upper_triangle(x: Any) -> Any:
    """Reference ``fused_softmax_mask_upper_triangle``: causal-masked softmax
    over the last two dims (scores [B, H, Sq, Sk])."""
    from paddle_tpu.core.dispatch import call_op

    def _impl(x):
        s_q, s_k = x.shape[-2], x.shape[-1]
        keep = jnp.tril(jnp.ones((s_q, s_k), bool))
        z = jnp.where(keep, x.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(x.dtype)

    return call_op("fused_softmax_mask_upper_triangle", _impl, x)


__all__ += ["fused_softmax_mask", "fused_softmax_mask_upper_triangle"]
