"""Fused functional ops (reference ``python/paddle/incubate/nn/functional/``:
fused_rms_norm, swiglu, fused_rotary_position_embedding, fused_bias_act, …).

Each maps to a composition that XLA fuses on TPU (or a Pallas kernel where
profiling says XLA's fusion is insufficient — see ``paddle_tpu.kernels``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import defop
from paddle_tpu.nn.functional.activation import swiglu  # noqa: F401
from paddle_tpu.nn.functional.common import rms_norm

__all__ = [
    "fused_rms_norm",
    "fused_layer_norm",
    "swiglu",
    "fused_rotary_position_embedding",
    "fused_bias_act",
    "fused_linear",
    "fused_bias_dropout_residual_layer_norm",
    "fused_dropout_add",
    "masked_multihead_attention",
    "block_multihead_attention",
    "block_multihead_attention_fused",
    "block_multihead_chunk_attention",
    "block_multihead_chunk_attention_fused",
    "block_cache_prefill",
    "block_cache_append",
    "block_cache_append_chunk",
    "block_cache_cow_copy",
    "BlockKVCache",
    "fused_moe",
]

from paddle_tpu.incubate.nn.functional.block_attention import (  # noqa: E402,F401
    BlockKVCache,
    block_cache_append,
    block_cache_append_chunk,
    block_cache_cow_copy,
    block_cache_prefill,
    block_multihead_attention,
    block_multihead_attention_fused,
    block_multihead_chunk_attention,
    block_multihead_chunk_attention_fused,
)
from paddle_tpu.incubate.nn.functional.fused_moe import fused_moe  # noqa: E402,F401


def fused_rms_norm(
    x: Any,
    norm_weight: Any,
    norm_bias: Any = None,
    epsilon: float = 1e-6,
    begin_norm_axis: int = -1,
    bias: Any = None,
    residual: Any = None,
    quant_scale: float = -1,
    **kwargs: Any,
) -> Tuple[Any, ...]:
    """Reference ``fused_rms_norm`` (rms_norm kernel + optional bias/residual
    add). Returns (out, residual_out) like the reference when residual given."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(
    x: Any,
    norm_weight: Any,
    norm_bias: Any = None,
    epsilon: float = 1e-5,
    begin_norm_axis: int = -1,
    bias: Any = None,
    residual: Any = None,
    **kwargs: Any,
) -> Any:
    from paddle_tpu.nn.functional.common import layer_norm

    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = layer_norm(x, None, norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, residual_out
    return out


# -- rope: XLA composition + rotation adjoint (pure array functions) ---------

def _rope_rotate(x, use_neox):
    if use_neox:
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate([-x2, x1], axis=-1)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _rope_broadcast_tables(x, sin, cos):
    s, c = sin, cos
    if s.ndim == 2:
        s = s[None, :, None, :]
        c = c[None, :, None, :]
    return s.astype(x.dtype), c.astype(x.dtype)


def _rope_apply_xla(x, sin, cos, use_neox):
    s, c = _rope_broadcast_tables(x, sin, cos)
    return x * c + _rope_rotate(x, use_neox) * s


def _rope_adjoint_xla(g, sin, cos, use_neox):
    """dx for y = x⊙c + rot(x)⊙s: ``g⊙c + unrot(g⊙s)`` — the rotation's
    adjoint is its inverse sign pattern (exact for asymmetric tables)."""
    s, c = _rope_broadcast_tables(g, sin, cos)
    gs = g * s
    if use_neox:
        half = g.shape[-1] // 2
        v1, v2 = gs[..., :half], gs[..., half:]
        unrot = jnp.concatenate([v2, -v1], axis=-1)
    else:
        v1 = gs[..., 0::2]
        v2 = gs[..., 1::2]
        unrot = jnp.stack([v2, -v1], axis=-1).reshape(gs.shape)
    return g * c + unrot


@defop("fused_rotary_position_embedding", tensor_method=None)
def _fused_rope_op(q, k, v, sin, cos, use_neox_rotary_style=True):
    """RoPE (reference ``fused_ops.yaml:408`` fused_rotary_position_embedding;
    kernel ``paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu``).
    Layout [B, S, H, D]; sin/cos [1, S, 1, D] (or [S, D]).

    Registered raw op = the pure-XLA composition (parity audits, infer_meta,
    and create_graph re-differentiation trace THIS, never a Pallas call);
    the serving/train entry :func:`fused_rotary_position_embedding` routes
    around the generic ``jax.vjp`` dispatch with an explicit tape node whose
    backward runs the Pallas adjoint kernel directly."""
    return tuple(
        _rope_apply_xla(t, sin, cos, use_neox_rotary_style)
        for t in (q, k, v)
        if t is not None
    )


def _rope_kernel_tables(x, sin, cos, use_neox):
    """(cos2, sin2) in the Pallas kernel's [S, D] layout when this shape is
    kernel-eligible, else None. Per-batch tables (leading dim > 1 — decode
    with ragged positions) cannot collapse to [S, D]: XLA path only."""
    if not use_neox or x.shape[-1] % 128 != 0:
        return None
    if cos.ndim == 2:
        return cos, sin
    if cos.shape[0] == 1:
        return (
            cos.reshape(cos.shape[1], cos.shape[-1]),
            sin.reshape(sin.shape[1], sin.shape[-1]),
        )
    return None


def _rope_fwd_array(x, sin, cos, use_neox):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    tabs = _rope_kernel_tables(x, sin, cos, use_neox)
    if tabs is not None and pallas_enabled("use_pallas_fused"):
        try:
            from paddle_tpu.kernels.fused import fused_rope_pallas

            return fused_rope_pallas(x, tabs[0], tabs[1])
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_rope", exc)
    return _rope_apply_xla(x, sin, cos, use_neox)


def _rope_bwd_array(g, sin, cos, use_neox):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    tabs = _rope_kernel_tables(g, sin, cos, use_neox)
    if tabs is not None and pallas_enabled("use_pallas_fused"):
        try:
            from paddle_tpu.kernels.fused import rope_adjoint_pallas

            return rope_adjoint_pallas(g, tabs[0], tabs[1])
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_rope_bwd", exc)
    return _rope_adjoint_xla(g, sin, cos, use_neox)


def _reduce_to_shape(arr, shape):
    """Sum ``arr`` down to broadcast source ``shape`` (table cotangents)."""
    while arr.ndim > len(shape):
        arr = arr.sum(axis=0)
    for ax, (have, want) in enumerate(zip(arr.shape, shape)):
        if want == 1 and have != 1:
            arr = arr.sum(axis=ax, keepdims=True)
    return arr.reshape(shape)


def fused_rotary_position_embedding(
    q: Any,
    k: Any = None,
    v: Any = None,
    sin: Any = None,
    cos: Any = None,
    position_ids: Any = None,
    use_neox_rotary_style: bool = True,
    time_major: bool = False,
    rotary_emb_base: float = 10000.0,
) -> Tuple[Any, ...]:
    """RoPE over q/k/v with an EXPLICIT tape backward.

    The generic op dispatch differentiates its forward with ``jax.vjp`` at
    record time; routed through the Pallas rope kernel's ``custom_vjp`` that
    linearization is exactly what degraded to XLA on the r03 TPU run
    ("Linearization failed to produce known values for all output primals"
    — counted in ``paddle_tpu_kernel_fallbacks_total{kernel=fused_rope}``).
    This entry instead records a manual :class:`~paddle_tpu.core.autograd.
    GradNode` (the ``recompute`` pattern): forward and backward each run
    their own standalone Pallas kernel (``fused_rope_pallas`` /
    ``rope_adjoint_pallas``) behind the usual applicability gate + XLA
    fallback, and NO jax AD transform ever sees a ``pallas_call`` — there is
    nothing left to fail linearization. ``create_graph`` re-differentiation
    goes through the registered pure-XLA raw op.
    """
    from paddle_tpu.core import autograd as _ag
    from paddle_tpu.core import dispatch as _dispatch
    from paddle_tpu.core.tensor import Tensor

    if sin is None or cos is None:
        # build sin/cos table from base
        b, s, h, d = q.shape
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        sin = Tensor(jnp.sin(emb))
        cos = Tensor(jnp.cos(emb))

    neox = bool(use_neox_rotary_style)
    inputs = [q, k, v, sin, cos]
    arrays = [
        (t._data if isinstance(t, Tensor) else (None if t is None else jnp.asarray(t)))
        for t in inputs
    ]
    # AMP autocast parity with call_op: a custom_white/black_list naming this
    # op must still cast its tensor inputs even though dispatch is manual
    from paddle_tpu.amp.auto_cast import amp_cast_inputs, amp_enabled

    if amp_enabled():
        present = [i for i, a in enumerate(arrays) if a is not None]
        cast = amp_cast_inputs(
            "fused_rotary_position_embedding", [arrays[i] for i in present]
        )
        for i, a in zip(present, cast):
            arrays[i] = a
    xq, xk, xv, s_arr, c_arr = arrays
    in_positions = [i for i in (0, 1, 2) if arrays[i] is not None]  # q/k/v present
    out_arrays = [_rope_fwd_array(arrays[i], s_arr, c_arr, neox) for i in in_positions]

    def _diff(t: Any) -> bool:
        return (
            isinstance(t, Tensor)
            and not t.stop_gradient
            and jnp.issubdtype(jnp.dtype(t.dtype), jnp.inexact)
        )

    record = _ag.is_grad_enabled() and any(_diff(t) for t in inputs)
    node = None
    if record:
        diff_pos = [i for i, t in enumerate(inputs) if _diff(t)]
        diff_tensors = [inputs[i] for i in diff_pos]
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_arrays]
        _flat, out_treedef = jax.tree_util.tree_flatten(tuple(out_arrays))
        # output index for each q/k/v position (outs pack only non-None)
        out_index = {pos: j for j, pos in enumerate(in_positions)}
        consts = list(arrays)  # non-diff inputs closed over as arrays

        def vjp_fn(cots: Any) -> Tuple[Any, ...]:
            # out_treedef is always set, so the sweep hands us the tuple form
            cot_list = list(cots)
            grads: List[Any] = []
            for pos in diff_pos:
                if pos in out_index:  # q/k/v: one standalone adjoint kernel
                    g = cot_list[out_index[pos]]
                    grads.append(_rope_bwd_array(g, s_arr, c_arr, neox))
                    continue
                # table cotangents (rare — tables are buffers in every real
                # model): exact sums over the XLA composition's broadcast
                total = None
                for p in in_positions:
                    g32 = cot_list[out_index[p]].astype(jnp.float32)
                    x32 = arrays[p].astype(jnp.float32)
                    term = (
                        g32 * _rope_rotate(x32, neox)
                        if pos == 3  # sin
                        else g32 * x32  # cos
                    )
                    total = term if total is None else total + term
                src = s_arr if pos == 3 else c_arr
                shape = (
                    src.shape if src.ndim != 2
                    else (1, src.shape[0], 1, src.shape[1])
                )
                red = _reduce_to_shape(total, shape).reshape(src.shape)
                grads.append(red.astype(src.dtype))
            return tuple(grads)

        def closed(*diff_arrays: Any) -> Tuple[Any, ...]:
            vals = list(consts)
            for p, arr in zip(diff_pos, diff_arrays):
                vals[p] = arr
            return tuple(
                _rope_apply_xla(vals[i], vals[3], vals[4], neox)
                for i in in_positions
            )

        node = _ag.GradNode(
            "fused_rotary_position_embedding", vjp_fn, diff_tensors, out_avals,
            fwd_fn=closed, out_treedef=out_treedef,
        )

    if _dispatch._NAN_CHECK[0]:
        _dispatch._check_nan_inf("fused_rotary_position_embedding", out_arrays)
    if _dispatch.op_stats_hook is not None:  # amp.debugging operator stats
        _dispatch.op_stats_hook("fused_rotary_position_embedding", out_arrays)
    result: List[Any] = []
    for j, _pos in enumerate(in_positions):
        t = Tensor(out_arrays[j], stop_gradient=(node is None))
        if node is not None:
            t._grad_node = node
            t._grad_output_index = j
        result.append(t)
    while len(result) < 3:
        result.append(None)
    return tuple(result[:3])


@defop("fused_bias_act", tensor_method=None)
def fused_bias_act(x, bias=None, act_method="gelu", dequant_scales=None, shift=None, smooth=None, **kwargs):
    """Reference ``fused_ops.yaml:201`` fused_bias_act."""
    if bias is not None:
        x = x + bias
    if act_method in ("gelu",):
        return jax.nn.gelu(x)
    if act_method in ("relu",):
        return jax.nn.relu(x)
    if act_method in ("swiglu", "silu"):
        if act_method == "swiglu":
            a, b = jnp.split(x, 2, axis=-1)
            return jax.nn.silu(a) * b
        return jax.nn.silu(x)
    raise ValueError(f"unsupported act_method {act_method}")


@defop("fused_linear", tensor_method=None)
def fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    out = jnp.matmul(x, w)
    if bias is not None:
        out = out + bias
    return out


@defop("masked_multihead_attention", tensor_method=None)
def masked_multihead_attention(q, k, v, cache_k, cache_v, seq_len, scale=None):
    """Decode-phase attention with append-to-cache — the static-shape KV-cache
    attention step (reference ``paddle/phi/ops/yaml/ops.yaml:3074``
    ``masked_multihead_attention_``, CUDA kernel
    ``paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu``).

    One new token per sequence attends to every cached position up to its
    current length; the new K/V are written into fixed-size buffers with
    ``dynamic_update_slice`` so every decode step is the SAME compiled XLA
    program (no shape growth, no recompiles — the TPU analog of the
    reference's in-place `_` op).

    Args:
      q/k/v: ``[B, 1, H, D]`` / ``[B, 1, HK, D]`` this step's post-RoPE
        projections (GQA: HK may divide H).
      cache_k/cache_v: ``[B, S_max, HK, D]`` static cache buffers.
      seq_len: int32 scalar or ``[B]`` — tokens already cached; the new token
        is written at this index.
      scale: attention scale, default ``1/sqrt(D)``.

    Returns ``(out [B, 1, H, D], cache_k', cache_v')``.
    """
    b, _, h, d = q.shape
    hk = cache_k.shape[2]
    s_max = cache_k.shape[1]
    group = h // hk
    if scale is None:
        scale = 1.0 / (d**0.5)
    lens = jnp.broadcast_to(jnp.asarray(seq_len, jnp.int32).reshape(-1), (b,))
    try:
        # concrete lengths (eager decode loops): fail loudly on overflow —
        # inside jit the write index would silently clamp onto the last slot
        concrete = np.asarray(lens)
        if (concrete >= s_max).any():
            raise ValueError(
                f"KV cache overflow: seq_len {concrete.max()} >= buffer size {s_max}"
            )
    except (jax.errors.TracerArrayConversionError, jax.errors.ConcretizationTypeError):
        pass

    def append(buf, new, ln):
        # buf [S_max, HK, D], new [1, HK, D]
        return jax.lax.dynamic_update_slice(buf, new.astype(buf.dtype), (ln, 0, 0))

    ck = jax.vmap(append)(cache_k, k, lens)
    cv = jax.vmap(append)(cache_v, v, lens)

    qg = q.reshape(b, 1, hk, group, d).astype(jnp.float32)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, ck.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max, dtype=jnp.int32)
    allowed = pos[None, :] <= lens[:, None]  # include the just-written token
    logits = jnp.where(allowed[:, None, None, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p, cv.astype(jnp.float32))
    return out.reshape(b, 1, h, d).astype(q.dtype), ck, cv


def fused_bias_dropout_residual_layer_norm(
    x: Any,
    residual: Any,
    bias: Any = None,
    ln_scale: Any = None,
    ln_bias: Any = None,
    dropout_rate: float = 0.0,
    ln_epsilon: float = 1e-5,
    training: bool = True,
    mode: str = "upscale_in_train",
) -> Any:
    from paddle_tpu.nn.functional.common import dropout, layer_norm

    if bias is not None:
        x = x + bias
    x = dropout(x, p=dropout_rate, training=training, mode=mode)
    x = x + residual
    return layer_norm(x, None, ln_scale, ln_bias, ln_epsilon)


def fused_dropout_add(x: Any, y: Any, p: float = 0.5, training: bool = True, mode: str = "upscale_in_train") -> Any:
    from paddle_tpu.nn.functional.common import dropout

    return dropout(x, p=p, training=training, mode=mode) + y


def fused_softmax_mask(x: Any, mask: Any) -> Any:
    """Reference ``fused_softmax_mask kernel``: softmax(x + mask) in one
    fused step (XLA fuses the add into the softmax)."""
    from paddle_tpu.core.dispatch import call_op

    def _impl(x, m):
        return jax.nn.softmax(x.astype(jnp.float32) + m.astype(jnp.float32), axis=-1).astype(x.dtype)

    return call_op("fused_softmax_mask", _impl, x, mask)


def fused_softmax_mask_upper_triangle(x: Any) -> Any:
    """Reference ``fused_softmax_mask_upper_triangle``: causal-masked softmax
    over the last two dims (scores [B, H, Sq, Sk])."""
    from paddle_tpu.core.dispatch import call_op

    def _impl(x):
        s_q, s_k = x.shape[-2], x.shape[-1]
        keep = jnp.tril(jnp.ones((s_q, s_k), bool))
        z = jnp.where(keep, x.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(x.dtype)

    return call_op("fused_softmax_mask_upper_triangle", _impl, x)


__all__ += ["fused_softmax_mask", "fused_softmax_mask_upper_triangle"]


# -- fused residual-add + norm: the decode layer's epilogue pairs ------------
#
# One transformer layer's epilogue is two HBM round-trips — ``r = x +
# residual`` then ``y = norm(r)`` — issued twice per layer (post-attention
# and pre-next-layer). These entries collapse each pair into ONE Pallas
# dispatch behind the usual gate, with the XLA fallback running the EXACT op
# composition the unfused path runs (x + residual, then ``rms_norm``'s
# upcast/rsqrt/downcast/weight order, or ``layer_norm``'s no-upcast order) —
# which is what keeps fused on/off byte-identical per backend. Backward is
# the PR 9 explicit tape-GradNode pattern: a standalone adjoint kernel that
# recomputes rstd from the saved residual stream, with no jax AD transform
# ever applied over a ``pallas_call``.


def _rms_res_fwd_array(x, residual, weight, eps):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if (
        weight.dtype == x.dtype
        and x.shape[-1] % 128 == 0
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import fused_rms_norm_residual_pallas

            return fused_rms_norm_residual_pallas(x, residual, weight, eps)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_rms_norm_residual", exc)
    r = x + residual
    xf = r.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    out = out.astype(r.dtype)
    return out * weight, r


def _rms_res_bwd_array(g, r, weight, eps):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if (
        weight.dtype == g.dtype
        and g.shape[-1] % 128 == 0
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import rms_norm_residual_adjoint_pallas

            return rms_norm_residual_adjoint_pallas(g, r, weight, eps)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_rms_norm_residual_bwd", exc)
    r32 = r.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(r32), axis=-1, keepdims=True) + eps)
    xhat = r32 * rstd
    gw = g32 * weight.astype(jnp.float32)
    dot = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - xhat * dot)).astype(g.dtype)
    dw = jnp.sum((g32 * xhat).reshape(-1, r.shape[-1]), axis=0).astype(weight.dtype)
    return dx, dw


def _ln_res_fwd_array(x, residual, weight, bias, eps):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if (
        weight.dtype == x.dtype
        and x.shape[-1] % 128 == 0
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import fused_layer_norm_residual_pallas

            return fused_layer_norm_residual_pallas(x, residual, weight, bias, eps)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_layer_norm_residual", exc)
    # the exact nn.functional.common.layer_norm composition: stats in the IO
    # dtype (no upcast), weight multiply then bias add only when present
    r = x + residual
    mean = jnp.mean(r, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(r - mean), axis=-1, keepdims=True)
    out = (r - mean) * jax.lax.rsqrt(var + eps)
    out = out * weight
    if bias is not None:
        out = out + bias
    return out, r


def _ln_res_bwd_array(g, r, weight, eps):
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if (
        weight.dtype == g.dtype
        and g.shape[-1] % 128 == 0
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import layer_norm_residual_adjoint_pallas

            return layer_norm_residual_adjoint_pallas(g, r, weight, eps)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_layer_norm_residual_bwd", exc)
    r32 = r.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    mu = jnp.mean(r32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(r32 - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (r32 - mu) * rstd
    gw = g32 * weight.astype(jnp.float32)
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - m1 - xhat * m2)).astype(g.dtype)
    h = r.shape[-1]
    dw = jnp.sum((g32 * xhat).reshape(-1, h), axis=0).astype(weight.dtype)
    db = jnp.sum(g32.reshape(-1, h), axis=0).astype(weight.dtype)
    return dx, dw, db


def _residual_norm_entry(name, x, norm_weight, norm_bias, residual, eps, is_rms):
    """Shared tape-GradNode plumbing for the two residual+norm entries.

    Outputs ``(y, residual_out)`` as Tensors. The residual add's adjoint is
    the identity, so the node hands ``d_r = norm_adjoint(dy) + d_residual_out``
    to BOTH x and residual; weight (and bias) cotangents come from the same
    standalone adjoint kernel. ``create_graph`` re-differentiation traces the
    pure-XLA ``closed`` composition — never a pallas_call.
    """
    from paddle_tpu.core import autograd as _ag
    from paddle_tpu.core import dispatch as _dispatch
    from paddle_tpu.core.tensor import Tensor

    inputs = [x, norm_weight, norm_bias, residual]
    arrays = [
        (t._data if isinstance(t, Tensor) else (None if t is None else jnp.asarray(t)))
        for t in inputs
    ]
    from paddle_tpu.amp.auto_cast import amp_cast_inputs, amp_enabled

    if amp_enabled():
        present = [i for i, a in enumerate(arrays) if a is not None]
        cast = amp_cast_inputs(name, [arrays[i] for i in present])
        for i, a in zip(present, cast):
            arrays[i] = a
    xa, wa, ba, ra = arrays
    if is_rms:
        y, r = _rms_res_fwd_array(xa, ra, wa, eps)
    else:
        y, r = _ln_res_fwd_array(xa, ra, wa, ba, eps)
    out_arrays = [y, r]

    def _diff(t: Any) -> bool:
        return (
            isinstance(t, Tensor)
            and not t.stop_gradient
            and jnp.issubdtype(jnp.dtype(t.dtype), jnp.inexact)
        )

    record = _ag.is_grad_enabled() and any(_diff(t) for t in inputs)
    node = None
    if record:
        diff_pos = [i for i, t in enumerate(inputs) if _diff(t)]
        diff_tensors = [inputs[i] for i in diff_pos]
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_arrays]
        _flat, out_treedef = jax.tree_util.tree_flatten(tuple(out_arrays))
        consts = list(arrays)

        def vjp_fn(cots: Any) -> Tuple[Any, ...]:
            gy, gr = cots
            if gy is None:
                gy = jnp.zeros(out_avals[0].shape, out_avals[0].dtype)
            if is_rms:
                dr, dw = _rms_res_bwd_array(gy, r, wa, eps)
                db = None
            else:
                dr, dw, db = _ln_res_bwd_array(gy, r, wa, eps)
            if gr is not None:
                dr = dr + gr.astype(dr.dtype)
            by_pos = {0: dr, 1: dw, 2: db, 3: dr}
            return tuple(by_pos[p] for p in diff_pos)

        def closed(*diff_arrays: Any) -> Tuple[Any, ...]:
            vals = list(consts)
            for p, arr in zip(diff_pos, diff_arrays):
                vals[p] = arr
            rr = vals[0] + vals[3]
            if is_rms:
                xf = rr.astype(jnp.float32)
                var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
                out = (xf * jax.lax.rsqrt(var + eps)).astype(rr.dtype) * vals[1]
            else:
                mu = jnp.mean(rr, axis=-1, keepdims=True)
                var = jnp.mean(jnp.square(rr - mu), axis=-1, keepdims=True)
                out = (rr - mu) * jax.lax.rsqrt(var + eps) * vals[1]
                if vals[2] is not None:
                    out = out + vals[2]
            return out, rr

        node = _ag.GradNode(
            name, vjp_fn, diff_tensors, out_avals,
            fwd_fn=closed, out_treedef=out_treedef,
        )

    if _dispatch._NAN_CHECK[0]:
        _dispatch._check_nan_inf(name, out_arrays)
    if _dispatch.op_stats_hook is not None:  # amp.debugging operator stats
        _dispatch.op_stats_hook(name, out_arrays)
    result = []
    for j, arr in enumerate(out_arrays):
        t = Tensor(arr, stop_gradient=(node is None))
        if node is not None:
            t._grad_node = node
            t._grad_output_index = j
        result.append(t)
    return tuple(result)


def fused_rms_norm_residual(
    x: Any, norm_weight: Any, residual: Any, epsilon: float = 1e-6
) -> Tuple[Any, Any]:
    """``r = x + residual; y = rms_norm(r, norm_weight)`` as ONE dispatch with
    an explicit tape backward (standalone adjoint kernel — no jax AD over the
    pallas_call). Returns ``(y, r)``; ``r`` feeds the next residual hop."""
    return _residual_norm_entry(
        "fused_rms_norm_residual", x, norm_weight, None, residual,
        float(epsilon), True,
    )


def fused_layer_norm_residual(
    x: Any, norm_weight: Any, norm_bias: Any, residual: Any,
    epsilon: float = 1e-5,
) -> Tuple[Any, Any]:
    """``r = x + residual; y = layer_norm(r, norm_weight, norm_bias)`` as ONE
    dispatch with an explicit tape backward. Returns ``(y, r)``."""
    return _residual_norm_entry(
        "fused_layer_norm_residual", x, norm_weight, norm_bias, residual,
        float(epsilon), False,
    )


def fused_embed_rms_norm(
    input_ids: Any, embed_weight: Any, norm_weight: Any, epsilon: float = 1e-6
) -> Tuple[Any, Any]:
    """Chunk-step entry fusion: token-id gather + embedding lookup + first
    decoder layer's pre-attention RMSNorm in ONE dispatch (the scalar-
    prefetched ids steer the embedding-row BlockSpec). Inference-only — the
    serving step never differentiates; training embeds through the regular
    op. Returns ``(emb, y)`` Tensors: the raw rows (residual stream seed) and
    their normed form."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    table = embed_weight._data if isinstance(embed_weight, Tensor) else jnp.asarray(embed_weight)
    w = norm_weight._data if isinstance(norm_weight, Tensor) else jnp.asarray(norm_weight)
    eps = float(epsilon)
    if (
        w.dtype == table.dtype
        and table.shape[-1] % 128 == 0
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import fused_embed_rms_norm_pallas

            emb, y = fused_embed_rms_norm_pallas(ids, table, w, eps)
            return Tensor(emb, stop_gradient=True), Tensor(y, stop_gradient=True)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_embed_norm", exc)
    # exact unfused composition: XLA gather, then rms_norm's op order
    emb = table[ids]
    xf = emb.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = (xf * jax.lax.rsqrt(var + eps)).astype(emb.dtype) * w
    return Tensor(emb, stop_gradient=True), Tensor(y, stop_gradient=True)


__all__ += [
    "fused_rms_norm_residual",
    "fused_layer_norm_residual",
    "fused_embed_rms_norm",
]
