"""Paged (blocked) KV-cache attention for serving.

Reference: ``block_multihead_attention_`` (``fused_ops.yaml:45``, CUDA kernel
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``) — the
vLLM-style paged cache: KV lives in fixed-size physical blocks; a per-sequence
``block_table`` maps logical block index → physical block id, so sequences
grow without reserving max_seq_len per slot and freed blocks are reused.

TPU-native shape: the cache is a dense ``[num_blocks, H, block_size, D]``
array (heads OUTSIDE the token dim, so one head's physical block tiles as an
``(block_size, D)`` VMEM plane); appends are batched scatters
(``.at[phys, :, off].set``) and decode attention runs the Pallas block-table
flash-decode kernel (``kernels/paged_attention.py``) when enabled, falling
back to a dense gather with a static ``max_blocks_per_seq`` bound — all
static shapes, so the whole decode step jits once. The block allocator is
host-side Python (it runs between steps, not inside the program), mirroring
the reference where block tables are produced by the serving scheduler.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.testing.faults import fault_point as _fault_point

__all__ = [
    "BlockKVCache",
    "block_multihead_attention",
    "block_cache_prefill",
    "block_cache_append",
]


class BlockKVCache:
    """Host-side paged-cache manager: physical block pool + per-sequence block
    tables (reference: the serving scheduler that feeds ``block_tables``)."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        num_heads: int,
        head_dim: int,
        max_blocks_per_seq: int,
        dtype: Any = jnp.bfloat16,
    ) -> None:
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # [NB, H, BS, D]: heads OUTSIDE the token dim so a TPU kernel block
        # (one head, one physical block) tiles as (BS, D) — (8k, 128)-friendly
        self._shape = (int(num_blocks), int(num_heads), int(block_size), int(head_dim))
        self._dtype = dtype
        # device buffers are LAZY: callers that only use the host-side
        # allocator/tables (e.g. generate_paged, which owns per-layer pools)
        # never pay this HBM
        self._key_cache = None
        self._value_cache = None
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}  # seq id -> list of physical block ids
        self._lens: dict = {}  # seq id -> tokens stored

    @property
    def key_cache(self) -> Any:
        if self._key_cache is None:
            self._key_cache = jnp.zeros(self._shape, self._dtype)
        return self._key_cache

    @key_cache.setter
    def key_cache(self, v: Any) -> None:
        self._key_cache = v

    @property
    def value_cache(self) -> Any:
        if self._value_cache is None:
            self._value_cache = jnp.zeros(self._shape, self._dtype)
        return self._value_cache

    @value_cache.setter
    def value_cache(self, v: Any) -> None:
        self._value_cache = v

    # -- allocator ----------------------------------------------------------
    def allocate(self, seq_id: int, num_tokens: int) -> None:
        """Ensure ``seq_id`` has blocks for ``num_tokens`` more tokens."""
        _fault_point("block_pool.allocate")
        table = self._tables.setdefault(seq_id, [])
        cur = self._lens.get(seq_id, 0)
        need_blocks = -(-(cur + num_tokens) // self.block_size)
        while len(table) < need_blocks:
            if not self._free:
                raise MemoryError("paged KV cache out of physical blocks")
            if len(table) >= self.max_blocks_per_seq:
                raise MemoryError(
                    f"sequence {seq_id} exceeds max_blocks_per_seq={self.max_blocks_per_seq}"
                )
            table.append(self._free.pop())
        self._lens[seq_id] = cur + num_tokens

    def free(self, seq_id: int) -> None:
        """Return a finished sequence's blocks to the pool."""
        for b in self._tables.pop(seq_id, []):
            self._free.append(b)
        self._lens.pop(seq_id, None)

    def truncate(self, seq_id: int, num_tokens: int) -> None:
        """Roll ``seq_id`` back to ``num_tokens`` stored tokens, returning
        now-unused tail blocks to the pool — the undo for a speculative or
        failed step whose ``allocate`` already ran."""
        table = self._tables.get(seq_id)
        if table is None:
            return
        keep = -(-num_tokens // self.block_size) if num_tokens > 0 else 0
        while len(table) > keep:
            self._free.append(table.pop())
        self._lens[seq_id] = num_tokens

    def seq_len(self, seq_id: int) -> int:
        return self._lens.get(seq_id, 0)

    def blocks_allocated(self, seq_id: Optional[int] = None) -> int:
        """Physical blocks held by ``seq_id`` (all sequences when None) —
        the public accounting surface the serving engine's admission math
        relies on."""
        if seq_id is not None:
            return len(self._tables.get(seq_id, ()))
        return sum(len(t) for t in self._tables.values())

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def block_table(self, seq_ids: Sequence[int]) -> jnp.ndarray:
        """Dense ``[B, max_blocks_per_seq]`` table (unused slots point at
        block 0; masking makes them unreachable)."""
        out = np.zeros((len(seq_ids), self.max_blocks_per_seq), np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables.get(sid, [])
            out[i, : len(t)] = t
        return jnp.asarray(out)

    def seq_lens(self, seq_ids: Sequence[int]) -> jnp.ndarray:
        return jnp.asarray([self._lens.get(s, 0) for s in seq_ids], jnp.int32)


def block_cache_append(
    key_cache: jax.Array,  # [NB, H, BS, D]
    value_cache: jax.Array,
    k: jax.Array,  # [B, H, D] one new token per sequence
    v: jax.Array,
    block_tables: jax.Array,  # [B, MBS]
    positions: jax.Array,  # [B] token index being written (0-based)
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
) -> Tuple[jax.Array, jax.Array]:
    """Scatter one new KV token per sequence into its physical block slot.

    With ``slot_mask``, masked-off (padded) batch slots write NOTHING: their
    block-table row may alias physical blocks owned by live sequences (the
    engine keeps evicted rows at 0), so their scatter is routed out of bounds
    and dropped instead of clobbering another sequence's KV."""
    nb, _h, bs, _d = key_cache.shape
    blk_idx = positions // bs
    off = positions % bs
    phys = jnp.take_along_axis(block_tables, blk_idx[:, None], axis=1)[:, 0]
    if slot_mask is not None:
        phys = jnp.where(slot_mask, phys, nb)
    key_cache = key_cache.at[phys, :, off].set(k.astype(key_cache.dtype), mode="drop")
    value_cache = value_cache.at[phys, :, off].set(v.astype(value_cache.dtype), mode="drop")
    return key_cache, value_cache


def block_cache_prefill(
    key_cache: jax.Array,
    value_cache: jax.Array,
    k: jax.Array,  # [B, S, H, D] prompt KV
    v: jax.Array,
    block_tables: jax.Array,  # [B, MBS]
    seq_lens: jax.Array,  # [B] prompt lengths (<= S)
) -> Tuple[jax.Array, jax.Array]:
    """Write whole prompts into the paged cache (encoder phase of the
    reference kernel). Positions past ``seq_lens`` scatter into a scratch
    slot (block 0 / slot recomputed) are avoided via clamping + final mask."""
    b, s, h, d = k.shape
    nb, bs = key_cache.shape[0], key_cache.shape[2]
    t = jnp.arange(s)[None, :]  # [1, S]
    valid = t < seq_lens[:, None]  # [B, S]
    blk_idx = jnp.minimum(t // bs, block_tables.shape[1] - 1)
    off = t % bs
    phys = jnp.take_along_axis(block_tables, blk_idx, axis=1)  # [B, S]
    # invalid positions are routed OUT OF BOUNDS and dropped by the scatter —
    # clamping them onto a real block would collide with a valid write at the
    # same slot, and duplicate-index scatter order is undefined
    phys = jnp.where(valid, phys, nb)
    flat_phys = phys.reshape(-1)
    flat_off = jnp.broadcast_to(off, phys.shape).reshape(-1)
    flat_k = k.reshape(b * s, h, d).astype(key_cache.dtype)
    flat_v = v.reshape(b * s, h, d).astype(value_cache.dtype)
    key_cache = key_cache.at[flat_phys, :, flat_off].set(flat_k, mode="drop")
    value_cache = value_cache.at[flat_phys, :, flat_off].set(flat_v, mode="drop")
    return key_cache, value_cache


def block_multihead_attention(
    q: jax.Array,  # [B, 1, HQ, D] decode query (one token per sequence)
    k: jax.Array,  # [B, 1, HKV, D] new key
    v: jax.Array,  # [B, 1, HKV, D] new value
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens already cached (EXCLUDING this one)
    scale: Optional[float] = None,
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One paged-cache decode step: append the new KV, attend over the
    sequence's blocks. Returns ``(out [B, 1, HQ, D], key_cache, value_cache)``
    — pass donated caches under jit for true in-place update (the reference
    op is declared ``inplace``).

    ``slot_mask`` is the continuous-batching engine's ragged-batch contract:
    masked-off slots append nothing, attend over nothing (their effective
    length is forced to 0 so the ragged kernel skips them entirely), and
    return exactly zeros — in lockstep between the Pallas kernel and this XLA
    fallback so slot padding never changes numerics."""
    b, one, hq, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / (d**0.5)
    key_cache, value_cache = block_cache_append(
        key_cache, value_cache, k[:, 0], v[:, 0], block_tables, seq_lens,
        slot_mask=slot_mask,
    )
    # length INCLUDING the freshly appended token; 0 for padded slots
    attend_lens = seq_lens + 1
    if slot_mask is not None:
        attend_lens = jnp.where(slot_mask, attend_lens, 0)
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if pallas_enabled("use_pallas_paged_attention"):
        # block-table flash-decode kernel: streams only this sequence's
        # physical blocks HBM -> VMEM (no dense [B, MBS*BS, H, D] gather).
        # Applicability is checked with a cached host-side lowering probe
        # BEFORE the kernel is baked into the trace — a Mosaic error inside
        # a jitted decode step could not be caught here at run time.
        from paddle_tpu.kernels.paged_attention import (
            lowering_supported,
            paged_flash_decode,
        )

        nb, hkv_c, bs, d_c = key_cache.shape
        if lowering_supported(
            b, hq, hkv_c, d_c, nb, bs, block_tables.shape[1], str(q.dtype)
        ):
            try:
                out = paged_flash_decode(
                    q[:, 0], key_cache, value_cache, block_tables,
                    attend_lens,  # kernel masks pos < len INCLUDING this token
                    scale=scale,
                )
                return out[:, None], key_cache, value_cache
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("paged_flash_decode", exc)
        else:
            warn_fallback(
                "paged_flash_decode", RuntimeError("Mosaic lowering unsupported for geometry")
            )
    # gather each sequence's blocks: [B, MBS, HKV, BS, D] -> [B, L, HKV, D]
    gk = jnp.moveaxis(key_cache[block_tables], 2, 3)
    gv = jnp.moveaxis(value_cache[block_tables], 2, 3)
    mbs, bs = block_tables.shape[1], key_cache.shape[2]
    L = mbs * bs
    gk = gk.reshape(b, L, hkv, d)
    gv = gv.reshape(b, L, hkv, d)
    if hkv != hq:
        if hq % hkv != 0:
            raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
        rep = hq // hkv
        gk = jnp.repeat(gk, rep, axis=2)
        gv = jnp.repeat(gv, rep, axis=2)
    qf = q[:, 0].astype(jnp.float32) * scale  # [B, HQ, D]
    scores = jnp.einsum("bhd,blhd->bhl", qf, gk.astype(jnp.float32))
    pos = jnp.arange(L)[None, None, :]
    mask = pos < attend_lens[:, None, None]  # attends the freshly-appended token
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhl,blhd->bhd", probs, gv.astype(jnp.float32))
    if slot_mask is not None:
        # fully-masked softmax degenerates to a uniform mean over garbage;
        # the kernel emits exact zeros for skipped slots — match it
        out = jnp.where(slot_mask[:, None, None], out, 0.0)
    return out[:, None].astype(q.dtype), key_cache, value_cache
