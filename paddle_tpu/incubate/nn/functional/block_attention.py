"""Paged (blocked) KV-cache attention for serving.

Reference: ``block_multihead_attention_`` (``fused_ops.yaml:45``, CUDA kernel
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``) — the
vLLM-style paged cache: KV lives in fixed-size physical blocks; a per-sequence
``block_table`` maps logical block index → physical block id, so sequences
grow without reserving max_seq_len per slot and freed blocks are reused.

TPU-native shape: the cache is a dense ``[num_blocks, H, block_size, D]``
array (heads OUTSIDE the token dim, so one head's physical block tiles as an
``(block_size, D)`` VMEM plane); appends are batched scatters
(``.at[phys, :, off].set``) and decode attention runs the Pallas block-table
flash-decode kernel (``kernels/paged_attention.py``) when enabled, falling
back to a dense gather with a static ``max_blocks_per_seq`` bound — all
static shapes, so the whole decode step jits once. The block allocator is
host-side Python (it runs between steps, not inside the program), mirroring
the reference where block tables are produced by the serving scheduler.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.testing.faults import fault_point as _fault_point


def _current_tp_mesh() -> Optional[Any]:
    """The tensor-parallel shard group armed by the serving engine's
    dispatch (``distributed/tp.py``), read at TRACE time. Checked through
    ``sys.modules`` so the single-chip path never imports the distributed
    package: if no engine ever armed a tp mesh, the module is absent and
    this is one dict lookup."""
    mod = sys.modules.get("paddle_tpu.distributed.tp")
    return mod.current_tp_mesh() if mod is not None else None


def _tp_sharded_flash_chunk(
    q: jax.Array,
    key_cache: jax.Array,
    value_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    q_lens: jax.Array,
    scale: float,
    mesh: Any,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Run the mixed ragged Pallas kernel PER SHARD over the head partition:
    a ``pallas_call`` has no SPMD partitioning rule, so under a tp mesh the
    kernel must be shard_mapped — each shard walks its own head slice of its
    own pool partition (head-parallel attention needs no communication
    inside the paged block walk; tables/lens are replicated host data).
    Quantization scale planes ([NB, KVH, BS]) partition on the SAME head
    axis as the KV planes they describe — scales are just more pool data.
    ``interpret`` runs the per-shard kernel in Pallas interpret mode so the
    shard split itself is testable off-TPU."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import shard_map
    from paddle_tpu.kernels.paged_attention import paged_flash_chunk

    in_specs = [
        P(None, None, "tp", None),  # q [B, C, HQ, D]: heads split
        P(None, "tp", None, None),  # key_cache [NB, KVH, BS, D]
        P(None, "tp", None, None),  # value_cache
        P(None, None),  # block_tables: replicated host truth
        P(None),  # seq_lens
        P(None),  # q_lens
    ]
    operands = [q, key_cache, value_cache, block_tables, seq_lens, q_lens]
    if k_scale is not None:
        in_specs += [P(None, "tp", None), P(None, "tp", None)]
        operands += [k_scale, v_scale]

    def _shard_chunk_attend(q_l, kc_l, vc_l, tables_l, lens_l, qlens_l,
                            ks_l=None, vs_l=None):
        return paged_flash_chunk(
            q_l, kc_l, vc_l, tables_l, lens_l, qlens_l, scale=scale,
            interpret=interpret, k_scale=ks_l, v_scale=vs_l,
        )

    return shard_map(
        _shard_chunk_attend,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, None, "tp", None),
        check_vma=False,
    )(*operands)

def _tp_sharded_flash_chunk_fused(
    q: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    key_cache: jax.Array,
    value_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    q_lens: jax.Array,
    scale: float,
    mesh: Any,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """:func:`_tp_sharded_flash_chunk` for the rope-fused kernel: the rope
    rows are position data shared by every head, so they ride replicated
    while q/caches (and scale planes) split over the head partition."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import shard_map
    from paddle_tpu.kernels.paged_attention import paged_flash_chunk_fused

    in_specs = [
        P(None, None, "tp", None),  # q [B, C, HQ, D]: heads split
        P(None, None, None),  # cos [B, C, D]: replicated position data
        P(None, None, None),  # sin
        P(None, "tp", None, None),  # key_cache [NB, KVH, BS, D]
        P(None, "tp", None, None),  # value_cache
        P(None, None),  # block_tables: replicated host truth
        P(None),  # seq_lens
        P(None),  # q_lens
    ]
    operands = [q, cos, sin, key_cache, value_cache, block_tables,
                seq_lens, q_lens]
    if k_scale is not None:
        in_specs += [P(None, "tp", None), P(None, "tp", None)]
        operands += [k_scale, v_scale]

    def _shard_chunk_attend(q_l, cos_l, sin_l, kc_l, vc_l, tables_l, lens_l,
                            qlens_l, ks_l=None, vs_l=None):
        return paged_flash_chunk_fused(
            q_l, cos_l, sin_l, kc_l, vc_l, tables_l, lens_l, qlens_l,
            scale=scale, interpret=interpret, k_scale=ks_l, v_scale=vs_l,
        )

    return shard_map(
        _shard_chunk_attend,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, None, "tp", None),
        check_vma=False,
    )(*operands)


__all__ = [
    "BlockKVCache",
    "block_multihead_attention",
    "block_multihead_attention_fused",
    "block_multihead_chunk_attention",
    "block_multihead_chunk_attention_fused",
    "block_cache_prefill",
    "block_cache_append",
    "block_cache_append_chunk",
    "block_cache_cow_copy",
]


class BlockKVCache:
    """Host-side paged-cache manager: physical block pool + per-sequence block
    tables (reference: the serving scheduler that feeds ``block_tables``).

    Two allocation surfaces share the one physical free list:

    - the historical per-sequence table API (``allocate``/``free``/
      ``block_table``) used by ``generate_paged``, where a sequence owns its
      blocks exclusively; and
    - a reference-counted per-block API (``acquire_block``/``incref``/
      ``decref``) used by the prefix-cache layer
      (``inference/prefix_cache.py``), where one physical block may be mapped
      by many requests' block tables at once and is returned to the free list
      only when the last owner drops it.

    All accounting is guarded by one internal lock: the serving front end
    pumps the engine from a daemon thread while intake threads size requests
    against ``free_blocks``, so the pool's counters must never be read
    mid-update.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        num_heads: int,
        head_dim: int,
        max_blocks_per_seq: int,
        dtype: Any = jnp.bfloat16,
    ) -> None:
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # [NB, H, BS, D]: heads OUTSIDE the token dim so a TPU kernel block
        # (one head, one physical block) tiles as (BS, D) — (8k, 128)-friendly
        self._shape = (int(num_blocks), int(num_heads), int(block_size), int(head_dim))
        self._dtype = dtype
        # device buffers are LAZY: callers that only use the host-side
        # allocator/tables (e.g. generate_paged, which owns per-layer pools)
        # never pay this HBM
        self._key_cache = None
        self._value_cache = None
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}  # seq id -> list of physical block ids
        self._lens: dict = {}  # seq id -> tokens stored
        self._ref: Dict[int, int] = {}  # block id -> refcount (refcounted API)

    @property
    def key_cache(self) -> Any:
        if self._key_cache is None:
            self._key_cache = jnp.zeros(self._shape, self._dtype)
        return self._key_cache

    @key_cache.setter
    def key_cache(self, v: Any) -> None:
        self._key_cache = v

    @property
    def value_cache(self) -> Any:
        if self._value_cache is None:
            self._value_cache = jnp.zeros(self._shape, self._dtype)
        return self._value_cache

    @value_cache.setter
    def value_cache(self, v: Any) -> None:
        self._value_cache = v

    # -- quantized-pool surface (FLAGS_kv_cache_dtype=int8) ------------------
    @property
    def quantized(self) -> bool:
        """True when the pool stores int8 blocks with companion scale planes."""
        return jnp.dtype(self._dtype) == jnp.int8

    @property
    def key_scale(self) -> Any:
        """Per-block-per-head-per-token fp32 scales ``[NB, H, BS]`` addressed
        by the SAME physical block ids as ``key_cache`` — every lifecycle seam
        (refcount, CoW, spill, recovery) moves cache rows and scale rows
        together. Initialized to ONES: ``quantize(zeros)`` yields ``q=0,
        scale=1``, so a fresh pool is byte-identical to a quantized empty one."""
        if getattr(self, "_key_scale", None) is None:
            self._key_scale = jnp.ones(self._shape[:3], jnp.float32)
        return self._key_scale

    @key_scale.setter
    def key_scale(self, v: Any) -> None:
        self._key_scale = v

    @property
    def value_scale(self) -> Any:
        if getattr(self, "_value_scale", None) is None:
            self._value_scale = jnp.ones(self._shape[:3], jnp.float32)
        return self._value_scale

    @value_scale.setter
    def value_scale(self, v: Any) -> None:
        self._value_scale = v

    # -- allocator ----------------------------------------------------------
    def allocate(self, seq_id: int, num_tokens: int) -> None:
        """Ensure ``seq_id`` has blocks for ``num_tokens`` more tokens."""
        _fault_point("block_pool.allocate")
        with self._lock:
            table = self._tables.setdefault(seq_id, [])
            cur = self._lens.get(seq_id, 0)
            need_blocks = -(-(cur + num_tokens) // self.block_size)
            while len(table) < need_blocks:
                if not self._free:
                    raise MemoryError("paged KV cache out of physical blocks")
                if len(table) >= self.max_blocks_per_seq:
                    raise MemoryError(
                        f"sequence {seq_id} exceeds max_blocks_per_seq={self.max_blocks_per_seq}"
                    )
                table.append(self._free.pop())
            self._lens[seq_id] = cur + num_tokens

    def free(self, seq_id: int) -> None:
        """Return a finished sequence's blocks to the pool."""
        with self._lock:
            for b in self._tables.pop(seq_id, []):
                self._free.append(b)
            self._lens.pop(seq_id, None)

    def truncate(self, seq_id: int, num_tokens: int) -> None:
        """Roll ``seq_id`` back to ``num_tokens`` stored tokens, returning
        now-unused tail blocks to the pool — the undo for a speculative or
        failed step whose ``allocate`` already ran."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                return
            keep = -(-num_tokens // self.block_size) if num_tokens > 0 else 0
            while len(table) > keep:
                self._free.append(table.pop())
            self._lens[seq_id] = num_tokens

    def seq_len(self, seq_id: int) -> int:
        with self._lock:
            return self._lens.get(seq_id, 0)

    def blocks_allocated(self, seq_id: Optional[int] = None) -> int:
        """Physical blocks held by ``seq_id`` (all sequences when None) —
        the public accounting surface the serving engine's admission math
        relies on. Refcounted blocks (prefix-cache layer) are not attributed
        to any sequence; use ``num_blocks - free_blocks`` for whole-pool
        occupancy."""
        with self._lock:
            if seq_id is not None:
                return len(self._tables.get(seq_id, ()))
            return sum(len(t) for t in self._tables.values())

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def block_table(self, seq_ids: Sequence[int]) -> jnp.ndarray:
        """Dense ``[B, max_blocks_per_seq]`` table (unused slots point at
        block 0; masking makes them unreachable)."""
        out = np.zeros((len(seq_ids), self.max_blocks_per_seq), np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                t = self._tables.get(sid, [])
                out[i, : len(t)] = t
        return jnp.asarray(out)

    def seq_lens(self, seq_ids: Sequence[int]) -> jnp.ndarray:
        with self._lock:
            return jnp.asarray(
                [self._lens.get(s, 0) for s in seq_ids], jnp.int32
            )

    # -- refcounted per-block API (prefix-cache layer) -----------------------
    def acquire_block(self) -> int:
        """Take one physical block off the free list with refcount 1. The
        block belongs to the CALLER's accounting (a request's block table or
        a prefix-cache chain node), not to any ``seq_id`` table."""
        _fault_point("block_pool.allocate")
        with self._lock:
            if not self._free:
                raise MemoryError("paged KV cache out of physical blocks")
            blk = self._free.pop()
            self._ref[blk] = 1
            return blk

    def acquire_blocks(self, n: int) -> List[int]:
        """Atomically take ``n`` physical blocks off the free list, each
        with refcount 1 — the landing-slot reservation for a host-tier
        prefetch: either every block of the spilled chain gets a slot in
        one step or none does (no partial chain to unwind). Raises
        MemoryError with the free list untouched on a shortfall."""
        _fault_point("block_pool.allocate")
        n = int(n)
        with self._lock:
            if len(self._free) < n:
                raise MemoryError(
                    f"paged KV cache cannot reserve {n} blocks "
                    f"({len(self._free)} free)"
                )
            out = [self._free.pop() for _ in range(n)]
            for blk in out:
                self._ref[blk] = 1
            return out

    def incref(self, block: int) -> int:
        """Add one owner to a refcounted block; returns the new count."""
        with self._lock:
            cur = self._ref.get(block)
            if cur is None:
                raise ValueError(f"block {block} is not refcount-managed")
            self._ref[block] = cur + 1
            return cur + 1

    def decref(self, block: int) -> bool:
        """Drop one owner; returns True when this freed the block."""
        with self._lock:
            cur = self._ref.get(block)
            if cur is None:
                raise ValueError(f"block {block} is not refcount-managed")
            if cur <= 1:
                del self._ref[block]
                self._free.append(block)
                return True
            self._ref[block] = cur - 1
            return False

    def refcount(self, block: int) -> int:
        """Current owner count of a refcounted block (0 if unmanaged)."""
        with self._lock:
            return self._ref.get(block, 0)

    def refcounts(self) -> Dict[int, int]:
        """Snapshot of every refcount-managed block's owner count (for
        invariant checks; copied under the lock)."""
        with self._lock:
            return dict(self._ref)


def _quantize_kv_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-token absmax int8 quantization over the head dim: each
    ``[..., D]`` row gets its own fp32 scale (``absmax / 127``; 1.0 for an
    all-zero row so dequant stays exact), so an incremental decode append
    never forces requantizing tokens already in the block. This is THE
    canonical quant composition: the write kernels, the host-tier capture
    and the recovery replay all call it, which is what makes replay
    deterministic to the byte."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def block_cache_append(
    key_cache: jax.Array,  # [NB, H, BS, D]
    value_cache: jax.Array,
    k: jax.Array,  # [B, H, D] one new token per sequence
    v: jax.Array,
    block_tables: jax.Array,  # [B, MBS]
    positions: jax.Array,  # [B] token index being written (0-based)
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, H, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """Scatter one new KV token per sequence into its physical block slot.

    With ``slot_mask``, masked-off (padded) batch slots write NOTHING: their
    block-table row may alias physical blocks owned by live sequences (the
    engine keeps evicted rows at 0), so their scatter is routed out of bounds
    and dropped instead of clobbering another sequence's KV.

    With ``key_scale``/``value_scale`` (the int8 pool), quantization happens
    INSIDE this fused write: the same scatter indices that place the int8
    rows place their per-token scales, so the scale table rides every
    lifecycle seam the KV planes do. Returns 4 arrays instead of 2."""
    nb, _h, bs, _d = key_cache.shape
    blk_idx = positions // bs
    off = positions % bs
    phys = jnp.take_along_axis(block_tables, blk_idx[:, None], axis=1)[:, 0]
    if slot_mask is not None:
        phys = jnp.where(slot_mask, phys, nb)
    if key_scale is not None:
        qk, sk = _quantize_kv_rows(k)  # [B, H, D] int8, [B, H] f32
        qv, sv = _quantize_kv_rows(v)
        key_cache = key_cache.at[phys, :, off].set(qk, mode="drop")
        value_cache = value_cache.at[phys, :, off].set(qv, mode="drop")
        key_scale = key_scale.at[phys, :, off].set(sk, mode="drop")
        value_scale = value_scale.at[phys, :, off].set(sv, mode="drop")
        return key_cache, value_cache, key_scale, value_scale
    key_cache = key_cache.at[phys, :, off].set(k.astype(key_cache.dtype), mode="drop")
    value_cache = value_cache.at[phys, :, off].set(v.astype(value_cache.dtype), mode="drop")
    return key_cache, value_cache


def block_cache_prefill(
    key_cache: jax.Array,
    value_cache: jax.Array,
    k: jax.Array,  # [B, S, H, D] prompt KV
    v: jax.Array,
    block_tables: jax.Array,  # [B, MBS]
    seq_lens: jax.Array,  # [B] prompt lengths (<= S)
    key_scale: Optional[jax.Array] = None,  # [NB, H, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """Write whole prompts into the paged cache (encoder phase of the
    reference kernel). Positions past ``seq_lens`` scatter into a scratch
    slot (block 0 / slot recomputed) are avoided via clamping + final mask.
    With scale planes the write quantizes in-flight (returns 4 arrays)."""
    b, s, h, d = k.shape
    nb, bs = key_cache.shape[0], key_cache.shape[2]
    t = jnp.arange(s)[None, :]  # [1, S]
    valid = t < seq_lens[:, None]  # [B, S]
    blk_idx = jnp.minimum(t // bs, block_tables.shape[1] - 1)
    off = t % bs
    phys = jnp.take_along_axis(block_tables, blk_idx, axis=1)  # [B, S]
    # invalid positions are routed OUT OF BOUNDS and dropped by the scatter —
    # clamping them onto a real block would collide with a valid write at the
    # same slot, and duplicate-index scatter order is undefined
    phys = jnp.where(valid, phys, nb)
    flat_phys = phys.reshape(-1)
    flat_off = jnp.broadcast_to(off, phys.shape).reshape(-1)
    if key_scale is not None:
        qk, sk = _quantize_kv_rows(k.reshape(b * s, h, d))
        qv, sv = _quantize_kv_rows(v.reshape(b * s, h, d))
        key_cache = key_cache.at[flat_phys, :, flat_off].set(qk, mode="drop")
        value_cache = value_cache.at[flat_phys, :, flat_off].set(qv, mode="drop")
        key_scale = key_scale.at[flat_phys, :, flat_off].set(sk, mode="drop")
        value_scale = value_scale.at[flat_phys, :, flat_off].set(sv, mode="drop")
        return key_cache, value_cache, key_scale, value_scale
    flat_k = k.reshape(b * s, h, d).astype(key_cache.dtype)
    flat_v = v.reshape(b * s, h, d).astype(value_cache.dtype)
    key_cache = key_cache.at[flat_phys, :, flat_off].set(flat_k, mode="drop")
    value_cache = value_cache.at[flat_phys, :, flat_off].set(flat_v, mode="drop")
    return key_cache, value_cache


def block_cache_cow_copy(
    key_cache: jax.Array,  # [NB, H, BS, D]
    value_cache: jax.Array,
    src: jax.Array,  # [B] int32 physical block to fork from
    dst: jax.Array,  # [B] int32 private destination (== NB: no-op, dropped)
    key_scale: Optional[jax.Array] = None,  # [NB, H, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """Copy-on-write fork: duplicate whole physical blocks ``src`` into
    ``dst`` so a request that diverges inside a shared (refcounted) block can
    reuse its cached prefix KV without ever writing to the shared copy.

    The no-fork case is routed through the scatter's ``drop`` mode (``dst ==
    num_blocks``), so the same compiled program serves steps with and without
    forks — the fork set is data, never shape. The whole copy is skipped via
    ``lax.cond`` when no slot forks this step (the overwhelmingly common
    decode-only step pays one predicate, not a gather/scatter per layer).

    With scale planes the SAME fork copies them too (inside the one
    ``lax.cond``): a forked int8 block is bit-identical to its source, scales
    included — no requantization on CoW. Returns 4 arrays then."""
    nb = key_cache.shape[0]
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    csrc = jnp.clip(src, 0, nb - 1)

    if key_scale is not None:
        def _copy4(kv):
            kc, vc, ks, vs = kv
            kc = kc.at[dst].set(kc[csrc], mode="drop")
            vc = vc.at[dst].set(vc[csrc], mode="drop")
            ks = ks.at[dst].set(ks[csrc], mode="drop")
            vs = vs.at[dst].set(vs[csrc], mode="drop")
            return kc, vc, ks, vs

        return jax.lax.cond(
            jnp.any(dst < nb), _copy4, lambda kv: kv,
            (key_cache, value_cache, key_scale, value_scale),
        )

    def _copy(kv):
        kc, vc = kv
        kc = kc.at[dst].set(kc[csrc], mode="drop")
        vc = vc.at[dst].set(vc[csrc], mode="drop")
        return kc, vc

    return jax.lax.cond(
        jnp.any(dst < nb), _copy, lambda kv: kv, (key_cache, value_cache)
    )


def block_cache_append_chunk(
    key_cache: jax.Array,  # [NB, H, BS, D]
    value_cache: jax.Array,
    k: jax.Array,  # [B, C, H, D] up to C new tokens per sequence
    v: jax.Array,
    block_tables: jax.Array,  # [B, MBS]
    seq_lens: jax.Array,  # [B] tokens already stored (chunk writes AFTER them)
    q_lens: jax.Array,  # [B] valid new tokens this step (<= C; 0 = none)
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, H, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """Scatter a ragged chunk of new KV per sequence into its physical
    blocks: token ``j`` of sequence ``b`` lands at logical position
    ``seq_lens[b] + j``. Rows past ``q_lens`` (and masked-off slots) are
    routed out of bounds and dropped — a decode row (``q_lens == 1``) and a
    prompt-chunk row (``q_lens == C``) ride the same scatter. With scale
    planes the write quantizes in-flight per token row (returns 4 arrays):
    the scale scatter uses the SAME out-of-bounds routing, so dropped KV rows
    drop their scales with them."""
    b, c, h, d = k.shape
    nb, bs = key_cache.shape[0], key_cache.shape[2]
    j = jnp.arange(c)[None, :]  # [1, C]
    pos = seq_lens[:, None] + j  # [B, C] absolute token index
    valid = j < q_lens[:, None]
    if slot_mask is not None:
        valid = valid & slot_mask[:, None]
    blk_idx = jnp.minimum(pos // bs, block_tables.shape[1] - 1)
    off = pos % bs
    phys = jnp.take_along_axis(block_tables, blk_idx, axis=1)  # [B, C]
    # invalid rows go OUT OF BOUNDS and are dropped by the scatter — clamping
    # them onto a real block would collide with valid writes (duplicate-index
    # scatter order is undefined), exactly the block_cache_prefill rule
    phys = jnp.where(valid, phys, nb)
    flat_phys = phys.reshape(-1)
    flat_off = off.reshape(-1)
    if key_scale is not None:
        qk, sk = _quantize_kv_rows(k.reshape(b * c, h, d))
        qv, sv = _quantize_kv_rows(v.reshape(b * c, h, d))
        key_cache = key_cache.at[flat_phys, :, flat_off].set(qk, mode="drop")
        value_cache = value_cache.at[flat_phys, :, flat_off].set(qv, mode="drop")
        key_scale = key_scale.at[flat_phys, :, flat_off].set(sk, mode="drop")
        value_scale = value_scale.at[flat_phys, :, flat_off].set(sv, mode="drop")
        return key_cache, value_cache, key_scale, value_scale
    flat_k = k.reshape(b * c, h, d).astype(key_cache.dtype)
    flat_v = v.reshape(b * c, h, d).astype(value_cache.dtype)
    key_cache = key_cache.at[flat_phys, :, flat_off].set(flat_k, mode="drop")
    value_cache = value_cache.at[flat_phys, :, flat_off].set(flat_v, mode="drop")
    return key_cache, value_cache


def _gather_chunk_attend(
    q: jax.Array,  # [B, C, HQ, D] (C == 1 for a pure decode step)
    key_cache: jax.Array,
    value_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,  # [B] tokens cached BEFORE the new rows
    attend_q: jax.Array,  # [B] valid new rows (0 = masked slot: exact zeros)
    scale: float,
    k_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """The ONE XLA dense-gather attention fallback shared by the decode and
    chunked paths: gather each sequence's physical blocks, mask each query
    row to its causal limit (``seq_lens + j + 1`` for row ``j``), fp32
    softmax. Rows past ``attend_q`` return exact zeros — lockstep with the
    Pallas kernels' skip, so slot padding never changes numerics. With scale
    planes, dequant (``x.astype(f32) * scale`` — the kernels' exact op
    composition) is applied right after the gather."""
    b, c, hq, d = q.shape
    hkv = key_cache.shape[1]
    # gather each sequence's blocks: [B, MBS, HKV, BS, D] -> [B, L, HKV, D]
    gk = jnp.moveaxis(key_cache[block_tables], 2, 3)
    gv = jnp.moveaxis(value_cache[block_tables], 2, 3)
    mbs, bs = block_tables.shape[1], key_cache.shape[2]
    L = mbs * bs
    gk = gk.reshape(b, L, hkv, d)
    gv = gv.reshape(b, L, hkv, d)
    if k_scale is not None:
        # per-token scales ride the same block-table gather as the KV rows
        gks = jnp.moveaxis(k_scale[block_tables], 2, 3).reshape(b, L, hkv)
        gvs = jnp.moveaxis(v_scale[block_tables], 2, 3).reshape(b, L, hkv)
        gk = gk.astype(jnp.float32) * gks[..., None]
        gv = gv.astype(jnp.float32) * gvs[..., None]
    if hkv != hq:
        if hq % hkv != 0:
            raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
        rep = hq // hkv
        gk = jnp.repeat(gk, rep, axis=2)
        gv = jnp.repeat(gv, rep, axis=2)
    qf = q.astype(jnp.float32) * scale  # [B, C, HQ, D]
    scores = jnp.einsum("bchd,blhd->bchl", qf, gk.astype(jnp.float32))
    pos = jnp.arange(L)[None, None, :]  # [1, 1, L]
    # query j sees cached history plus the chunk's own tokens 0..j (causal)
    limit = seq_lens[:, None] + jnp.arange(c)[None, :] + 1  # [B, C]
    mask = pos < limit[:, :, None]  # [B, C, L]
    scores = jnp.where(mask[:, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bchl,blhd->bchd", probs, gv.astype(jnp.float32))
    # rows past attend_q (and fully-masked slots) degenerate to a uniform
    # mean over garbage in softmax — force exact zeros, matching the kernels
    row_valid = jnp.arange(c)[None, :] < attend_q[:, None]  # [B, C]
    out = jnp.where(row_valid[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def block_multihead_chunk_attention(
    q: jax.Array,  # [B, C, HQ, D] ragged chunk of new tokens per sequence
    k: jax.Array,  # [B, C, HKV, D]
    v: jax.Array,
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens already cached (EXCLUDING this chunk)
    q_lens: jax.Array,  # [B] valid new tokens this step (1 = decode row)
    scale: Optional[float] = None,
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """One MIXED prefill/decode step over the paged cache — the chunked-
    prefill dispatch ("Ragged Paged Attention", arxiv 2604.15464): every
    batch row carries up to ``C`` new tokens; a decode row has ``q_lens ==
    1``, a prompt-chunk row up to ``C``. The chunk's KV is appended first, so
    query token ``j`` (absolute position ``seq_lens + j``) attends over every
    cached position ``<= seq_lens + j`` — causal within the chunk, full
    history before it. Rows past ``q_lens`` and masked-off slots return
    exactly zeros (lockstep with the Pallas kernel's skip).

    Returns ``(out [B, C, HQ, D], key_cache, value_cache)``, plus the
    updated ``(key_scale, value_scale)`` planes when given (the int8 pool:
    quantize-on-write in the same fused append, dequant inside the kernel's
    block walk — or the identical composition in the XLA fallback).
    """
    b, c, hq, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / (d**0.5)
    quantized = key_scale is not None
    if quantized:
        key_cache, value_cache, key_scale, value_scale = block_cache_append_chunk(
            key_cache, value_cache, k, v, block_tables, seq_lens, q_lens,
            slot_mask=slot_mask, key_scale=key_scale, value_scale=value_scale,
        )
    else:
        key_cache, value_cache = block_cache_append_chunk(
            key_cache, value_cache, k, v, block_tables, seq_lens, q_lens,
            slot_mask=slot_mask,
        )
    attend_q = q_lens
    if slot_mask is not None:
        attend_q = jnp.where(slot_mask, attend_q, 0)
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    def _ret(out):
        if quantized:
            return out, key_cache, value_cache, key_scale, value_scale
        return out, key_cache, value_cache

    if pallas_enabled("use_pallas_paged_attention"):
        # ragged mixed prefill/decode kernel: one grid walks each sequence's
        # physical blocks once, serving its decode row and its prompt-chunk
        # rows alike; applicability is probed host-side at trace time (a
        # Mosaic error inside the jitted step is uncatchable at run time).
        # Under a tensor-parallel mesh the kernel runs shard_mapped over the
        # head partition, so the probe uses the PER-SHARD geometry.
        from paddle_tpu.kernels.paged_attention import (
            chunk_lowering_supported,
            paged_flash_chunk,
        )

        nb, hkv_c, bs, d_c = key_cache.shape
        tp_mesh = _current_tp_mesh()
        ntp = tp_mesh.shape["tp"] if tp_mesh is not None else 1
        if chunk_lowering_supported(
            b, c, hq // ntp, hkv_c // ntp, d_c, nb, bs,
            block_tables.shape[1], str(q.dtype),
            kv_dtype=str(key_cache.dtype) if quantized else "",
        ):
            try:
                if quantized:
                    # injected dequant failure degrades THIS dispatch to the
                    # XLA fallback below (counted), never the engine's
                    # recovery path — the except arm swallows it
                    _fault_point("quant.dequant")
                if tp_mesh is not None:
                    out = _tp_sharded_flash_chunk(
                        q, key_cache, value_cache, block_tables,
                        seq_lens, attend_q, scale, tp_mesh,
                        k_scale=key_scale, v_scale=value_scale,
                    )
                else:
                    out = paged_flash_chunk(
                        q, key_cache, value_cache, block_tables,
                        seq_lens, attend_q, scale=scale,
                        k_scale=key_scale, v_scale=value_scale,
                    )
                return _ret(out)
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("paged_flash_chunk", exc)
        else:
            warn_fallback(
                "paged_flash_chunk",
                RuntimeError("Mosaic lowering unsupported for geometry"),
            )
    out = _gather_chunk_attend(
        q, key_cache, value_cache, block_tables, seq_lens, attend_q, scale,
        k_scale=key_scale, v_scale=value_scale,
    )
    return _ret(out)


def block_multihead_chunk_attention_fused(
    q: jax.Array,  # [B, C, HQ, D] PRE-rope ragged chunk of new tokens
    k: jax.Array,  # [B, C, HKV, D] PRE-rope new keys
    v: jax.Array,
    cos: jax.Array,  # [B, C, 1, D] offset-gathered rope rows (model layout)
    sin: jax.Array,
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens already cached (EXCLUDING this chunk)
    q_lens: jax.Array,  # [B] valid new tokens this step (1 = decode row)
    scale: Optional[float] = None,
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """:func:`block_multihead_chunk_attention` with RoPE folded in — the
    fused decode layer's attention entry (``FLAGS_use_fused_decode_layer``).

    Takes PRE-rope q/k plus the per-slot rope rows and collapses the layer's
    rope pass + attention to one kernel dispatch: k is rotated by the same
    XLA elementwise composition the unfused path uses (it fuses into the
    cache-append scatter), while q's rotation moves INSIDE the paged kernel's
    block walk. The XLA fallback stays in lockstep by applying the identical
    ``_rope_apply_xla`` to q before the shared dense-gather attention — so on
    a backend without the kernel (CPU reference), fused on/off execute the
    SAME op composition and outputs are byte-identical by construction.
    Scale planes follow the :func:`block_multihead_chunk_attention` contract
    (quantize AFTER the rope — the cache stores roped, quantized keys).
    """
    from paddle_tpu.incubate.nn.functional import _rope_apply_xla

    b, c, hq, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    quantized = key_scale is not None
    k = _rope_apply_xla(k, sin, cos, True)
    if quantized:
        key_cache, value_cache, key_scale, value_scale = block_cache_append_chunk(
            key_cache, value_cache, k, v, block_tables, seq_lens, q_lens,
            slot_mask=slot_mask, key_scale=key_scale, value_scale=value_scale,
        )
    else:
        key_cache, value_cache = block_cache_append_chunk(
            key_cache, value_cache, k, v, block_tables, seq_lens, q_lens,
            slot_mask=slot_mask,
        )
    attend_q = q_lens
    if slot_mask is not None:
        attend_q = jnp.where(slot_mask, attend_q, 0)
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    def _ret(out):
        if quantized:
            return out, key_cache, value_cache, key_scale, value_scale
        return out, key_cache, value_cache

    if pallas_enabled("use_pallas_paged_attention"):
        from paddle_tpu.kernels.paged_attention import (
            chunk_fused_lowering_supported,
            paged_flash_chunk_fused,
        )

        nb, hkv_c, bs, d_c = key_cache.shape
        tp_mesh = _current_tp_mesh()
        ntp = tp_mesh.shape["tp"] if tp_mesh is not None else 1
        cos3 = cos.reshape(b, c, d)
        sin3 = sin.reshape(b, c, d)
        if chunk_fused_lowering_supported(
            b, c, hq // ntp, hkv_c // ntp, d_c, nb, bs,
            block_tables.shape[1], str(q.dtype),
            kv_dtype=str(key_cache.dtype) if quantized else "",
        ):
            try:
                if quantized:
                    _fault_point("quant.dequant")
                if tp_mesh is not None:
                    out = _tp_sharded_flash_chunk_fused(
                        q, cos3, sin3, key_cache, value_cache, block_tables,
                        seq_lens, attend_q, scale, tp_mesh,
                        k_scale=key_scale, v_scale=value_scale,
                    )
                else:
                    out = paged_flash_chunk_fused(
                        q, cos3, sin3, key_cache, value_cache, block_tables,
                        seq_lens, attend_q, scale=scale,
                        k_scale=key_scale, v_scale=value_scale,
                    )
                return _ret(out)
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("paged_flash_chunk_fused", exc)
        else:
            warn_fallback(
                "paged_flash_chunk_fused",
                RuntimeError("Mosaic lowering unsupported for geometry"),
            )
    # lockstep fallback: the SAME rope composition the unfused path applies,
    # then the shared dense-gather attention
    q = _rope_apply_xla(q, sin, cos, True)
    out = _gather_chunk_attend(
        q, key_cache, value_cache, block_tables, seq_lens, attend_q, scale,
        k_scale=key_scale, v_scale=value_scale,
    )
    return _ret(out)


def block_multihead_attention(
    q: jax.Array,  # [B, 1, HQ, D] decode query (one token per sequence)
    k: jax.Array,  # [B, 1, HKV, D] new key
    v: jax.Array,  # [B, 1, HKV, D] new value
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens already cached (EXCLUDING this one)
    scale: Optional[float] = None,
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """One paged-cache decode step: append the new KV, attend over the
    sequence's blocks. Returns ``(out [B, 1, HQ, D], key_cache, value_cache)``
    — pass donated caches under jit for true in-place update (the reference
    op is declared ``inplace``) — plus the updated scale planes when given.

    ``slot_mask`` is the continuous-batching engine's ragged-batch contract:
    masked-off slots append nothing, attend over nothing (their effective
    length is forced to 0 so the ragged kernel skips them entirely), and
    return exactly zeros — in lockstep between the Pallas kernel and this XLA
    fallback so slot padding never changes numerics."""
    b, one, hq, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / (d**0.5)
    quantized = key_scale is not None
    if quantized:
        key_cache, value_cache, key_scale, value_scale = block_cache_append(
            key_cache, value_cache, k[:, 0], v[:, 0], block_tables, seq_lens,
            slot_mask=slot_mask, key_scale=key_scale, value_scale=value_scale,
        )
    else:
        key_cache, value_cache = block_cache_append(
            key_cache, value_cache, k[:, 0], v[:, 0], block_tables, seq_lens,
            slot_mask=slot_mask,
        )
    # length INCLUDING the freshly appended token; 0 for padded slots
    attend_lens = seq_lens + 1
    if slot_mask is not None:
        attend_lens = jnp.where(slot_mask, attend_lens, 0)
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    def _ret(out):
        if quantized:
            return out, key_cache, value_cache, key_scale, value_scale
        return out, key_cache, value_cache

    if pallas_enabled("use_pallas_paged_attention"):
        # block-table flash-decode kernel: streams only this sequence's
        # physical blocks HBM -> VMEM (no dense [B, MBS*BS, H, D] gather).
        # Applicability is checked with a cached host-side lowering probe
        # BEFORE the kernel is baked into the trace — a Mosaic error inside
        # a jitted decode step could not be caught here at run time.
        from paddle_tpu.kernels.paged_attention import (
            lowering_supported,
            paged_flash_decode,
        )

        nb, hkv_c, bs, d_c = key_cache.shape
        if lowering_supported(
            b, hq, hkv_c, d_c, nb, bs, block_tables.shape[1], str(q.dtype),
            kv_dtype=str(key_cache.dtype) if quantized else "",
        ):
            try:
                if quantized:
                    _fault_point("quant.dequant")
                out = paged_flash_decode(
                    q[:, 0], key_cache, value_cache, block_tables,
                    attend_lens,  # kernel masks pos < len INCLUDING this token
                    scale=scale,
                    k_scale=key_scale, v_scale=value_scale,
                )
                return _ret(out[:, None])
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("paged_flash_decode", exc)
        else:
            warn_fallback(
                "paged_flash_decode", RuntimeError("Mosaic lowering unsupported for geometry")
            )
    # the decode step IS the C == 1 chunk: one new row per sequence whose
    # causal limit is seq_lens + 1 (attend_lens), masked slots exact zeros
    out = _gather_chunk_attend(
        q, key_cache, value_cache, block_tables, seq_lens,
        attend_lens - seq_lens, scale,
        k_scale=key_scale, v_scale=value_scale,
    )
    return _ret(out.astype(q.dtype))


def block_multihead_attention_fused(
    q: jax.Array,  # [B, 1, HQ, D] PRE-rope decode query
    k: jax.Array,  # [B, 1, HKV, D] PRE-rope new key
    v: jax.Array,  # [B, 1, HKV, D] new value
    cos: jax.Array,  # [B, 1, 1, D] offset-gathered rope rows (model layout)
    sin: jax.Array,
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens already cached (EXCLUDING this one)
    scale: Optional[float] = None,
    slot_mask: Optional[jax.Array] = None,  # [B] bool; False = padded slot
    key_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    value_scale: Optional[jax.Array] = None,
):
    """:func:`block_multihead_attention` with RoPE folded in — the pure-decode
    counterpart of :func:`block_multihead_chunk_attention_fused`.

    Takes PRE-rope q/k plus the per-slot rope rows: k is rotated by the same
    XLA elementwise composition the unfused path uses (it fuses into the
    cache-append scatter) while q's rotation moves INSIDE the flash-decode
    block walk (``paged_flash_decode_fused``). The XLA fallback applies the
    identical ``_rope_apply_xla`` to q before the shared dense-gather
    attention, so fused on/off execute the same op composition off-TPU and
    outputs are byte-identical by construction.
    """
    from paddle_tpu.incubate.nn.functional import _rope_apply_xla

    b, one, hq, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    quantized = key_scale is not None
    k = _rope_apply_xla(k, sin, cos, True)
    if quantized:
        key_cache, value_cache, key_scale, value_scale = block_cache_append(
            key_cache, value_cache, k[:, 0], v[:, 0], block_tables, seq_lens,
            slot_mask=slot_mask, key_scale=key_scale, value_scale=value_scale,
        )
    else:
        key_cache, value_cache = block_cache_append(
            key_cache, value_cache, k[:, 0], v[:, 0], block_tables, seq_lens,
            slot_mask=slot_mask,
        )
    # length INCLUDING the freshly appended token; 0 for padded slots
    attend_lens = seq_lens + 1
    if slot_mask is not None:
        attend_lens = jnp.where(slot_mask, attend_lens, 0)
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    def _ret(out):
        if quantized:
            return out, key_cache, value_cache, key_scale, value_scale
        return out, key_cache, value_cache

    if pallas_enabled("use_pallas_paged_attention"):
        # rope-fused flash-decode kernel; same cached host-side lowering
        # probe contract as the unfused decode dispatch above — a Mosaic
        # error inside the jitted decode step is uncatchable at run time
        from paddle_tpu.kernels.paged_attention import (
            decode_fused_lowering_supported,
            paged_flash_decode_fused,
        )

        nb, hkv_c, bs, d_c = key_cache.shape
        cos3 = cos.reshape(b, 1, d)
        sin3 = sin.reshape(b, 1, d)
        if decode_fused_lowering_supported(
            b, hq, hkv_c, d_c, nb, bs, block_tables.shape[1], str(q.dtype),
            kv_dtype=str(key_cache.dtype) if quantized else "",
        ):
            try:
                if quantized:
                    _fault_point("quant.dequant")
                out = paged_flash_decode_fused(
                    q[:, 0], cos3, sin3, key_cache, value_cache,
                    block_tables,
                    attend_lens,  # kernel masks pos < len INCLUDING this token
                    scale=scale,
                    k_scale=key_scale, v_scale=value_scale,
                )
                return _ret(out[:, None])
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("paged_flash_decode_fused", exc)
        else:
            warn_fallback(
                "paged_flash_decode_fused",
                RuntimeError("Mosaic lowering unsupported for geometry"),
            )
    # lockstep fallback: the SAME rope composition the unfused path applies,
    # then the shared dense-gather attention (C == 1 chunk)
    q = _rope_apply_xla(q, sin, cos, True)
    out = _gather_chunk_attend(
        q, key_cache, value_cache, block_tables, seq_lens,
        attend_lens - seq_lens, scale,
        k_scale=key_scale, v_scale=value_scale,
    )
    return _ret(out.astype(q.dtype))
