"""FusedMultiTransformer — the serving transformer stack as ONE layer.

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py:1071``
(FusedMultiTransformer: N pre-LN decoder layers with per-layer weight LISTS,
driven by the fused CUDA kernels; the workhorse of PaddleNLP inference).

TPU-native shape: the per-layer math composes the framework's fused ops —
rms/layer norm, single fused QKV projection, rope, flash attention for
prefill, ``masked_multihead_attention`` static-cache decode — and the whole
N-layer stack is plain traced code, so one ``jit`` compiles prefill and each
decode step into single XLA programs. Weight lists mirror the reference
layout (qkv ``[3*H*D, E]`` fused, row-major linear/ffn) for state migration.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.ops.manipulation import concat, reshape

__all__ = ["FusedMultiTransformer"]


class FusedMultiTransformer(Layer):
    """N fused pre-LN transformer decoder layers over weight lists.

    Args mirror the reference constructor: ``embed_dim``, ``num_heads``,
    ``dim_feedforward``, ``num_layers``, plus optional per-layer weight lists
    (freshly initialized when omitted). ``normalize_before=True`` (pre-LN)
    is the only supported form, like the reference's fused kernels.

    ``forward(src, attn_mask=None, caches=None, time_step=None)``:
      - prefill: ``caches=None`` → causal flash attention; returns ``out``
        (and fresh caches when ``use_cache``).
      - decode: ``caches`` = per-layer ``(k, v)`` fixed-size buffers and
        ``time_step`` = current length → masked_multihead_attention step.
    """

    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dim_feedforward: int,
        dropout_rate: float = 0.0,
        activation: str = "gelu",
        normalize_before: bool = True,
        num_layers: int = 1,
        nranks: int = 1,
        trans_qkvw: bool = True,
        ring_id: int = -1,
        norm_type: str = "layernorm",
        use_neox_rotary_style: bool = False,
        epsilon: float = 1e-5,
    ) -> None:
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer supports pre-layernorm only (the "
                "reference's fused kernels are pre-LN as well)"
            )
        if norm_type not in ("layernorm", "rmsnorm"):
            raise ValueError(f"norm_type must be layernorm/rmsnorm, got {norm_type}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        self.norm_type = norm_type
        self.epsilon = epsilon
        self.use_neox_rotary_style = use_neox_rotary_style
        self.dropout_rate = dropout_rate

        import numpy as np

        rng = np.random.default_rng(0)

        def _w(shape, scale):
            def init(param, *_args):
                param._data = jnp.asarray(rng.normal(0, scale, shape).astype(np.float32))

            return self.create_parameter(list(shape), default_initializer=init)

        def _ones(shape):
            def init(param, *_args):
                param._data = jnp.ones(shape, jnp.float32)

            return self.create_parameter(list(shape), default_initializer=init)

        def _zeros(shape):
            def init(param, *_args):
                param._data = jnp.zeros(shape, jnp.float32)

            return self.create_parameter(list(shape), default_initializer=init)

        e, ff = embed_dim, dim_feedforward
        # swiglu is a gated split silu(a)*b, so ffn1 projects to 2*ff (the
        # reference fused_bias_act layout); everything else keeps width ff
        ff1 = 2 * ff if activation == "swiglu" else ff
        s1, s2 = 1.0 / np.sqrt(e), 1.0 / np.sqrt(ff)
        self.ln_scales = [_ones((e,)) for _ in range(num_layers)]
        self.ln_biases = [_zeros((e,)) for _ in range(num_layers)] if norm_type == "layernorm" else None
        # fused qkv: [3, num_heads, head_dim, embed_dim] (reference trans_qkvw layout)
        self.qkv_weights = [_w((3, num_heads, self.head_dim, e), s1) for _ in range(num_layers)]
        self.qkv_biases = [_zeros((3, num_heads, self.head_dim)) for _ in range(num_layers)]
        self.linear_weights = [_w((e, e), s1) for _ in range(num_layers)]
        self.linear_biases = [_zeros((e,)) for _ in range(num_layers)]
        self.ffn_ln_scales = [_ones((e,)) for _ in range(num_layers)]
        self.ffn_ln_biases = [_zeros((e,)) for _ in range(num_layers)] if norm_type == "layernorm" else None
        self.ffn1_weights = [_w((e, ff1), s1) for _ in range(num_layers)]
        self.ffn1_biases = [_zeros((ff1,)) for _ in range(num_layers)]
        self.ffn2_weights = [_w((ff, e), s2) for _ in range(num_layers)]
        self.ffn2_biases = [_zeros((e,)) for _ in range(num_layers)]
        for i in range(num_layers):
            self.add_parameter(f"ln_scale_{i}", self.ln_scales[i])
            self.add_parameter(f"qkv_weight_{i}", self.qkv_weights[i])
            self.add_parameter(f"qkv_bias_{i}", self.qkv_biases[i])
            self.add_parameter(f"linear_weight_{i}", self.linear_weights[i])
            self.add_parameter(f"linear_bias_{i}", self.linear_biases[i])
            self.add_parameter(f"ffn_ln_scale_{i}", self.ffn_ln_scales[i])
            self.add_parameter(f"ffn1_weight_{i}", self.ffn1_weights[i])
            self.add_parameter(f"ffn1_bias_{i}", self.ffn1_biases[i])
            self.add_parameter(f"ffn2_weight_{i}", self.ffn2_weights[i])
            self.add_parameter(f"ffn2_bias_{i}", self.ffn2_biases[i])
            if self.ln_biases is not None:
                self.add_parameter(f"ln_bias_{i}", self.ln_biases[i])
                self.add_parameter(f"ffn_ln_bias_{i}", self.ffn_ln_biases[i])

    # -- helpers -------------------------------------------------------------
    def _norm(self, x: Tensor, scale: Tensor, bias: Optional[Tensor]) -> Tensor:
        if self.norm_type == "rmsnorm":
            from paddle_tpu.incubate.nn.functional import fused_rms_norm

            return fused_rms_norm(x, scale, None, self.epsilon)
        return F.layer_norm(x, [self.embed_dim], scale, bias, self.epsilon)

    def _act(self, x: Tensor) -> Tensor:
        if self.activation == "gelu":
            return F.gelu(x)
        if self.activation == "relu":
            return F.relu(x)
        if self.activation == "swiglu":
            return F.swiglu(x)  # gated split: silu(x[..., :ff]) * x[..., ff:]
        if self.activation == "silu":
            return F.silu(x)
        raise ValueError(f"unsupported activation {self.activation!r}")

    def _attn(
        self,
        i: int,
        h: Tensor,
        attn_mask: Optional[Tensor],
        cache: Optional[Tuple[Tensor, Tensor]],
        time_step: Optional[Tensor],
        use_cache: bool,
        rotary_embs: Any = None,
    ) -> Any:
        b, s, e = h.shape
        nh, hd = self.num_heads, self.head_dim
        qkv_w = reshape(self.qkv_weights[i], [3 * nh * hd, e])
        qkv = h @ qkv_w.t() + reshape(self.qkv_biases[i], [3 * nh * hd])
        qkv = reshape(qkv, [b, s, 3, nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if rotary_embs is not None:
            # (cos, sin) tables [max_pos, head_dim]: prefill slices [0:s);
            # decode gathers the row at time_step (same position for the
            # whole batch — the reference decode convention)
            from paddle_tpu.incubate.nn.functional import (
                fused_rotary_position_embedding,
            )

            cos_tab, sin_tab = rotary_embs
            cos_a = cos_tab._data if isinstance(cos_tab, Tensor) else jnp.asarray(cos_tab)
            sin_a = sin_tab._data if isinstance(sin_tab, Tensor) else jnp.asarray(sin_tab)
            if cache is not None and time_step is not None:
                import jax

                ts = time_step._data if isinstance(time_step, Tensor) else jnp.asarray(time_step)
                cos_s = jax.lax.dynamic_slice_in_dim(cos_a, ts.reshape(()), s, axis=0)
                sin_s = jax.lax.dynamic_slice_in_dim(sin_a, ts.reshape(()), s, axis=0)
            else:
                cos_s, sin_s = cos_a[:s], sin_a[:s]
            q, k, _ = fused_rotary_position_embedding(
                q, k, None, sin=Tensor(sin_s), cos=Tensor(cos_s),
                use_neox_rotary_style=self.use_neox_rotary_style,
            )
        if cache is not None and time_step is not None:
            from paddle_tpu.incubate.nn.functional import masked_multihead_attention

            if attn_mask is not None:
                raise NotImplementedError(
                    "FusedMultiTransformer: attn_mask is not supported in the "
                    "cached decode path (masking there is governed by "
                    "time_step); pass attn_mask only for prefill"
                )
            out, ck, cv = masked_multihead_attention(
                q, k, v, cache[0], cache[1], time_step
            )
            return reshape(out, [b, s, e]), (ck, cv)
        if attn_mask is not None:
            # Reference semantics (fused_transformer.py:1071): the caller's
            # attn_mask IS the full visibility mask (causal+padding combined),
            # so it replaces the causal default. The flash kernel is
            # causal-only; route through the shared masked-attention op.
            m = attn_mask._data if isinstance(attn_mask, Tensor) else jnp.asarray(attn_mask)
            if m.dtype != jnp.bool_:
                # clamp to the framework's additive-mask floor (-1e30) so a
                # fully-masked row softmaxes finitely instead of to NaN
                m = jnp.maximum(m.astype(jnp.float32), -1e30)
            if m.ndim == 2:  # [s_q, s_k] -> broadcast over batch and heads
                m = m[None, None]
            elif m.ndim == 3:  # [b, s_q, s_k] -> [b, 1, s_q, s_k]
                m = m[:, None]
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=Tensor(m))
        else:
            out, _ = F.flash_attention(q, k, v, causal=True)
        new_cache = (k, v) if use_cache else None
        return reshape(out, [b, s, e]), new_cache

    # -- reference surface ---------------------------------------------------
    def forward(
        self,
        src: Tensor,
        attn_mask: Optional[Tensor] = None,
        caches: Optional[Sequence[Tuple[Tensor, Tensor]]] = None,
        pre_caches: Any = None,
        rotary_embs: Any = None,
        rotary_emb_dims: int = 0,
        seq_lens: Any = None,
        time_step: Optional[Tensor] = None,
    ) -> Any:
        use_cache = caches is not None or time_step is not None
        h = src
        new_caches: List[Tuple[Tensor, Tensor]] = []
        for i in range(self.num_layers):
            residual = h
            x = self._norm(h, self.ln_scales[i], self.ln_biases[i] if self.ln_biases else None)
            attn_out, cache_i = self._attn(
                i, x, attn_mask, caches[i] if caches is not None else None,
                time_step, use_cache, rotary_embs,
            )
            attn_out = attn_out @ self.linear_weights[i] + self.linear_biases[i]
            h = residual + attn_out
            residual = h
            x = self._norm(
                h, self.ffn_ln_scales[i], self.ffn_ln_biases[i] if self.ffn_ln_biases else None
            )
            x = self._act(x @ self.ffn1_weights[i] + self.ffn1_biases[i])
            x = x @ self.ffn2_weights[i] + self.ffn2_biases[i]
            h = residual + x
            if use_cache:
                new_caches.append(cache_i)
        if use_cache:
            return h, new_caches
        return h
