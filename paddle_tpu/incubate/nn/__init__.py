from paddle_tpu.incubate.nn import functional  # noqa: F401
from paddle_tpu.incubate.nn.fused_transformer import FusedMultiTransformer  # noqa: F401
