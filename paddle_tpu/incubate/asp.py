"""ASP — automatic structured (n:m) sparsity.

Reference: ``python/paddle/incubate/asp/`` (``utils.py`` mask generation,
``asp.py`` ASPHelper / ``prune_model`` / ``decorate``). The reference targets
Ampere sparse tensor cores; the TPU MXU has no 2:4 sparse mode, so here ASP
is an honest *algorithmic* capability: n:m-pruned weights (same training
recipe, same masks) with the mask re-applied after every optimizer step via
the decorated optimizer — the win on TPU is model-compression research parity
and the memory/bandwidth gains of shipping pruned weights, not a matmul
speedup.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "calculate_density", "check_mask_1d", "get_mask_1d", "check_mask_2d",
    "get_mask_2d_greedy", "create_mask", "check_sparsity", "prune_model",
    "decorate", "set_excluded_layers", "reset_excluded_layers",
    "OptimizerWithSparsityGuarantee",
]

_EXCLUDED: set = set()
# The pruning mask lives ON the Parameter (``p._asp_mask``), not in a
# module-level ``{id(param): mask}`` registry: after a pruned model is
# garbage-collected, CPython reuses object ids, so a registry entry keyed by
# a dead param's id could silently apply the dead model's mask to a fresh
# unrelated weight. Attribute storage makes the mask's lifetime exactly the
# parameter's, and decorate()d optimizers still pick masks up regardless of
# call order (reference allows decorate-then-prune).
_ASP_MASK_ATTR = "_asp_mask"


def calculate_density(x: Any) -> float:
    a = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def check_mask_1d(mat: Any, n: int = 2, m: int = 4) -> bool:
    """True when every 1-D window of ``m`` has at most ``n`` nonzeros
    (reference ``utils.py:check_mask_1d``)."""
    a = np.asarray(mat).reshape(-1)
    pad = (-len(a)) % m
    a = np.pad(a, (0, pad))
    return bool((np.count_nonzero(a.reshape(-1, m), axis=1) <= n).all())


def get_mask_1d(mat: Any, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the ``n`` largest-|w| of every ``m`` consecutive weights
    (reference ``utils.py:get_mask_1d``)."""
    a = np.asarray(mat)
    flat = a.reshape(-1)
    pad = (-len(flat)) % m
    padded = np.pad(flat, (0, pad))
    groups = np.abs(padded.reshape(-1, m))
    order = np.argsort(-groups, axis=1, kind="stable")
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(-1)[: len(flat)].reshape(a.shape).astype(a.dtype if a.dtype.kind == "f" else np.float32)


def check_mask_2d(mat: Any, n: int = 2, m: int = 4) -> bool:
    """True when every ``m x m`` block has <= ``n`` nonzeros per row AND per
    column (reference ``utils.py:check_mask_2d``)."""
    a = np.asarray(mat)
    if a.ndim != 2:
        a = a.reshape(a.shape[0], -1)
    rows = (-a.shape[0]) % m
    cols = (-a.shape[1]) % m
    a = np.pad(a, ((0, rows), (0, cols)))
    R, C = a.shape
    blocks = a.reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    nz = blocks != 0
    return bool(
        (nz.sum(axis=3) <= n).all() and (nz.sum(axis=2) <= n).all()
    )


def get_mask_2d_greedy(mat: Any, n: int = 2, m: int = 4) -> np.ndarray:
    """Greedy 2-D n:m mask (reference ``utils.py:get_mask_2d_greedy``): per
    ``m x m`` block, pick entries largest-first subject to per-row AND
    per-column budgets of ``n``."""
    a = np.asarray(mat)
    orig_shape = a.shape
    if a.ndim != 2:
        a = a.reshape(a.shape[0], -1)
    rows = (-a.shape[0]) % m
    cols = (-a.shape[1]) % m
    ap = np.pad(a, ((0, rows), (0, cols)))
    R, C = ap.shape
    mask = np.zeros_like(ap, dtype=np.float32)
    for bi in range(0, R, m):
        for bj in range(0, C, m):
            block = np.abs(ap[bi : bi + m, bj : bj + m])
            order = np.dstack(np.unravel_index(np.argsort(-block, axis=None), block.shape))[0]
            row_budget = np.full(m, n)
            col_budget = np.full(m, n)
            for r, c in order:
                if row_budget[r] > 0 and col_budget[c] > 0:
                    mask[bi + r, bj + c] = 1.0
                    row_budget[r] -= 1
                    col_budget[c] -= 1
    return mask[: a.shape[0], : a.shape[1]].reshape(orig_shape)


def create_mask(tensor: Any, func_name: str = "get_mask_1d", n: int = 2, m: int = 4) -> np.ndarray:
    fn = {"get_mask_1d": get_mask_1d, "get_mask_2d_greedy": get_mask_2d_greedy}[
        func_name if isinstance(func_name, str) else func_name.__name__
    ]
    return fn(tensor.numpy() if isinstance(tensor, Tensor) else tensor, n, m)


def check_sparsity(tensor: Any, func_name: str = "check_mask_1d", n: int = 2, m: int = 4) -> bool:
    fn = {"check_mask_1d": check_mask_1d, "check_mask_2d": check_mask_2d}[
        func_name if isinstance(func_name, str) else func_name.__name__
    ]
    return fn(tensor.numpy() if isinstance(tensor, Tensor) else tensor, n, m)


def set_excluded_layers(param_names: List[str], main_program: Any = None) -> None:
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program: Any = None) -> None:
    _EXCLUDED.clear()


def _prunable(name: str, param: Any) -> bool:
    if name in _EXCLUDED:
        return False
    # the reference prunes weight matrices of FC/conv layers, never
    # biases/norms; the n:m pattern needs at least one full group
    return (
        not param.stop_gradient
        and len(param.shape) >= 2
        and "weight" in name.split(".")[-1]
        and int(np.prod(param.shape)) >= 4
    )


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True) -> Dict[str, np.ndarray]:
    """Apply n:m masks to every prunable weight (reference
    ``asp.py:prune_model``). Returns ``{param_name: mask}`` — the same dict
    ``decorate`` keeps to re-mask after each optimizer step."""
    algo = {"mask_1d": "get_mask_1d", "mask_2d_greedy": "get_mask_2d_greedy",
            "mask_2d_best": "get_mask_2d_greedy"}[mask_algo]
    masks: Dict[str, np.ndarray] = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        mask = create_mask(p, algo, n, m)
        p._data = p._data * jnp.asarray(mask, p._data.dtype)
        if with_mask:
            masks[name] = mask
            setattr(p, _ASP_MASK_ATTR, jnp.asarray(mask, p._data.dtype))
    return masks


class OptimizerWithSparsityGuarantee:
    """Reference ``asp.py:949``: wraps an optimizer so every ``step()``
    re-applies the pruning masks — weights stay n:m sparse through training.
    Masks live on the Parameters themselves (:func:`prune_model` attaches
    them), so the reference's both call orders (prune-then-decorate AND
    decorate-then-prune) work and a mask can never outlive — or be
    mis-delivered to — its parameter. An explicit :meth:`attach_masks` is a
    per-optimizer override that beats the Parameter's own mask regardless of
    call order (id() keys are safe here: the optimizer keeps its parameters
    alive for this wrapper's whole lifetime)."""

    def __init__(self, optimizer: Any) -> None:
        self._optimizer = optimizer
        self._masks: Dict[int, Any] = {}  # explicit attach_masks overrides

    def attach_masks(self, model: Layer, masks: Dict[str, np.ndarray]) -> None:
        """Explicitly (re)attach masks; wins over prune_model's Parameter
        masks for THIS optimizer even if prune_model runs afterwards."""
        named = dict(model.named_parameters())
        for name, mask in masks.items():
            p = named[name]
            self._masks[id(p)] = jnp.asarray(mask, p._data.dtype)

    def step(self) -> None:
        self._optimizer.step()
        from paddle_tpu.core import autograd as _ag

        with _ag.set_grad_enabled(False):
            for p in self._optimizer._parameters:
                mask = self._masks.get(id(p), getattr(p, _ASP_MASK_ATTR, None))
                if mask is not None:
                    p._data = p._data * mask

    def __getattr__(self, name: str) -> Any:  # delegate everything else
        return getattr(self._optimizer, name)


def decorate(optimizer: Any) -> OptimizerWithSparsityGuarantee:
    """Reference ``asp.py:233``: returns the sparsity-preserving optimizer.
    Call :func:`prune_model` first, then ``attach_masks`` (or let
    ``prune_and_decorate`` do both)."""
    return OptimizerWithSparsityGuarantee(optimizer)


def prune_and_decorate(model: Layer, optimizer: Any, n: int = 2, m: int = 4,
                       mask_algo: str = "mask_1d") -> OptimizerWithSparsityGuarantee:
    """Convenience composition used by the tests: prune + decorate + attach."""
    masks = prune_model(model, n, m, mask_algo)
    opt = decorate(optimizer)
    opt.attach_masks(model, masks)
    return opt
