"""``paddle_tpu.vision`` (reference ``python/paddle/vision``): model zoo +
transforms + synthetic datasets for benchmarks."""

from paddle_tpu.vision import datasets, models, transforms  # noqa: F401
