from paddle_tpu.vision.models.resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
from paddle_tpu.vision.models.vgg import (  # noqa: F401
    VGG,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)
from paddle_tpu.vision.models.mobilenet import (  # noqa: F401
    MobileNetV1,
    MobileNetV2,
    MobileNetV3Large,
    MobileNetV3Small,
    mobilenet_v1,
    mobilenet_v2,
    mobilenet_v3_large,
    mobilenet_v3_small,
)
from paddle_tpu.vision.models.misc import (  # noqa: F401
    AlexNet,
    LeNet,
    ShuffleNetV2,
    SqueezeNet,
    alexnet,
    shufflenet_v2_x1_0,
    squeezenet1_0,
    squeezenet1_1,
)
from paddle_tpu.vision.models.densenet import (  # noqa: F401
    DenseNet,
    densenet121,
    densenet161,
    densenet169,
    densenet201,
)
from paddle_tpu.vision.models.inception import (  # noqa: F401
    GoogLeNet,
    InceptionV3,
    googlenet,
    inception_v3,
)
