"""DenseNet (reference ``python/paddle/vision/models/densenet.py``)."""

from __future__ import annotations

from typing import Any, List

import paddle_tpu.nn as nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169", "densenet201"]

_CFGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c: int, growth: int, bn_size: int, dropout: float) -> None:
        super().__init__()
        self.block = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False),
        )
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x: Any) -> Any:
        import paddle_tpu as paddle

        y = self.block(x)
        if self.dropout is not None:
            y = self.dropout(y)
        return paddle.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c: int, out_c: int) -> None:
        super().__init__()
        self.block = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False), nn.AvgPool2D(2, 2),
        )

    def forward(self, x: Any) -> Any:
        return self.block(x)


class DenseNet(nn.Layer):
    def __init__(self, layers: int = 121, bn_size: int = 4, dropout: float = 0.0,
                 num_classes: int = 1000, with_pool: bool = True) -> None:
        super().__init__()
        init_c, growth, blocks = _CFGS[layers]
        feats: List[Any] = [
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1),
        ]
        c = init_c
        for bi, n_layers in enumerate(blocks):
            for _ in range(n_layers):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x: Any) -> Any:
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def densenet121(pretrained: bool = False, **kw: Any) -> DenseNet:
    return DenseNet(121, **kw)


def densenet161(pretrained: bool = False, **kw: Any) -> DenseNet:
    return DenseNet(161, **kw)


def densenet169(pretrained: bool = False, **kw: Any) -> DenseNet:
    return DenseNet(169, **kw)


def densenet201(pretrained: bool = False, **kw: Any) -> DenseNet:
    return DenseNet(201, **kw)
