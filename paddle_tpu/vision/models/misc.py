"""LeNet / AlexNet / SqueezeNet / ShuffleNetV2 (reference
``python/paddle/vision/models/{lenet,alexnet,squeezenet,shufflenetv2}.py``)."""

from __future__ import annotations

from typing import Any, List

import paddle_tpu.nn as nn

__all__ = [
    "LeNet", "AlexNet", "SqueezeNet", "ShuffleNetV2",
    "alexnet", "squeezenet1_0", "squeezenet1_1", "shufflenet_v2_x1_0",
]


class LeNet(nn.Layer):
    """Reference ``lenet.py``: MNIST-scale convnet ([N, 1, 28, 28])."""

    def __init__(self, num_classes: int = 10) -> None:
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes)
            )

    def forward(self, x: Any) -> Any:
        x = self.features(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class AlexNet(nn.Layer):
    """Reference ``alexnet.py``."""

    def __init__(self, num_classes: int = 1000) -> None:
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(), nn.MaxPool2D(3, 2),
        )
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes),
            )
        self.pool = nn.AdaptiveAvgPool2D((6, 6))

    def forward(self, x: Any) -> Any:
        x = self.pool(self.features(x))
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class _Fire(nn.Layer):
    def __init__(self, in_c: int, squeeze: int, e1: int, e3: int) -> None:
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x: Any) -> Any:
        import paddle_tpu as paddle

        s = self.squeeze(x)
        return paddle.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """Reference ``squeezenet.py`` (1.0 / 1.1 variants)."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000) -> None:
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64), _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1),
        )

    def forward(self, x: Any) -> Any:
        x = self.classifier(self.features(x))
        return x.flatten(1)


def _channel_shuffle(x: Any, groups: int) -> Any:
    import paddle_tpu.nn.functional as F

    return F.channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c: int, out_c: int, stride: int) -> None:
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), nn.ReLU(),
            )
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1, groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False), nn.BatchNorm2D(branch), nn.ReLU(),
        )

    def forward(self, x: Any) -> Any:
        import paddle_tpu as paddle

        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """Reference ``shufflenetv2.py`` (x1.0)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000) -> None:
        super().__init__()
        stages = {0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                  1.5: [176, 352, 704, 1024], 2.0: [244, 488, 976, 2048]}[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU(),
        )
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        in_c = 24
        blocks: List[Any] = []
        for out_c, repeat in zip(stages[:3], (4, 8, 4)):
            blocks.append(_ShuffleUnit(in_c, out_c, 2))
            for _ in range(repeat - 1):
                blocks.append(_ShuffleUnit(out_c, out_c, 1))
            in_c = out_c
        self.stages = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, stages[3], 1, bias_attr=False),
            nn.BatchNorm2D(stages[3]), nn.ReLU(),
        )
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(stages[3], num_classes)

    def forward(self, x: Any) -> Any:
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        x = self.pool(x).flatten(1)
        return self.fc(x)


def alexnet(pretrained: bool = False, **kwargs: Any) -> AlexNet:
    return AlexNet(**kwargs)


def squeezenet1_0(pretrained: bool = False, **kwargs: Any) -> SqueezeNet:
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained: bool = False, **kwargs: Any) -> SqueezeNet:
    return SqueezeNet("1.1", **kwargs)


def shufflenet_v2_x1_0(pretrained: bool = False, **kwargs: Any) -> ShuffleNetV2:
    return ShuffleNetV2(scale=1.0, **kwargs)
