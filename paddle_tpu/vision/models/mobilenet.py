"""MobileNet V1/V2/V3 (reference ``python/paddle/vision/models/mobilenetv1.py``
/ ``mobilenetv2.py`` / ``mobilenetv3.py``). Depthwise convs are ``groups=C``
``Conv2D`` — XLA lowers them to TPU depthwise convolutions directly."""

from __future__ import annotations

from typing import Any, List, Optional

import paddle_tpu.nn as nn

__all__ = [
    "MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small", "mobilenet_v3_large",
]


def _make_divisible(v: float, divisor: int = 8, min_value: Optional[int] = None) -> int:
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(in_c: int, out_c: int, k: int, stride: int = 1, groups: int = 1,
             act: Any = nn.ReLU) -> nn.Sequential:
    layers: List[Any] = [
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=(k - 1) // 2,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c),
    ]
    if act is not None:
        layers.append(act())
    return nn.Sequential(*layers)


class MobileNetV1(nn.Layer):
    """Reference ``mobilenetv1.py``: depthwise-separable stacks."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True) -> None:
        super().__init__()
        s = lambda c: int(c * scale)  # noqa: E731
        cfg = [  # (out, stride) per depthwise-separable block
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
        ]
        layers: List[Any] = [_conv_bn(3, s(32), 3, stride=2)]
        in_c = s(32)
        for out, stride in cfg:
            layers.append(_conv_bn(in_c, in_c, 3, stride=stride, groups=in_c))
            layers.append(_conv_bn(in_c, s(out), 1))
            in_c = s(out)
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x: Any) -> Any:
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c: int, out_c: int, stride: int, expand: int) -> None:
        super().__init__()
        hidden = in_c * expand
        self.use_res = stride == 1 and in_c == out_c
        layers: List[Any] = []
        if expand != 1:
            layers.append(_conv_bn(in_c, hidden, 1, act=nn.ReLU6))
        layers.append(_conv_bn(hidden, hidden, 3, stride=stride, groups=hidden, act=nn.ReLU6))
        layers.append(_conv_bn(hidden, out_c, 1, act=None))
        self.conv = nn.Sequential(*layers)

    def forward(self, x: Any) -> Any:
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """Reference ``mobilenetv2.py``: inverted residuals with linear
    bottlenecks."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True) -> None:
        super().__init__()
        cfg = [  # t (expand), c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        layers: List[Any] = [_conv_bn(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_conv_bn(in_c, last, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x: Any) -> Any:
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class _SqueezeExcite(nn.Layer):
    def __init__(self, c: int, squeeze: int) -> None:
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze, 1)
        self.fc2 = nn.Conv2D(squeeze, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x: Any) -> Any:
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c: int, exp: int, out_c: int, k: int, stride: int,
                 se: bool, act: Any) -> None:
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers: List[Any] = []
        if exp != in_c:
            layers.append(_conv_bn(in_c, exp, 1, act=act))
        layers.append(_conv_bn(exp, exp, k, stride=stride, groups=exp, act=act))
        if se:
            layers.append(_SqueezeExcite(exp, _make_divisible(exp // 4)))
        layers.append(_conv_bn(exp, out_c, 1, act=None))
        self.conv = nn.Sequential(*layers)

    def forward(self, x: Any) -> Any:
        out = self.conv(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # k, exp, out, se, act, stride
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]
_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg: List, last_exp: int, scale: float, num_classes: int,
                 with_pool: bool) -> None:
        super().__init__()
        in_c = _make_divisible(16 * scale)
        layers: List[Any] = [_conv_bn(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, stride in cfg:
            layers.append(
                _V3Block(in_c, _make_divisible(exp * scale),
                         _make_divisible(out * scale), k, stride, se, act)
            )
            in_c = _make_divisible(out * scale)
        last_c = _make_divisible(last_exp * scale)
        layers.append(_conv_bn(in_c, last_c, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            hidden = 1024 if last_exp == 576 else 1280
            self.classifier = nn.Sequential(
                nn.Linear(last_c, hidden), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(hidden, num_classes),
            )

    def forward(self, x: Any) -> Any:
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True) -> None:
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True) -> None:
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


def mobilenet_v1(pretrained: bool = False, scale: float = 1.0, **kwargs: Any) -> MobileNetV1:
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained: bool = False, scale: float = 1.0, **kwargs: Any) -> MobileNetV2:
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained: bool = False, scale: float = 1.0, **kwargs: Any) -> MobileNetV3Small:
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained: bool = False, scale: float = 1.0, **kwargs: Any) -> MobileNetV3Large:
    return MobileNetV3Large(scale=scale, **kwargs)
