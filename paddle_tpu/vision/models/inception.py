"""GoogLeNet + InceptionV3 (reference
``python/paddle/vision/models/{googlenet,inceptionv3}.py``)."""

from __future__ import annotations

from typing import Any, List

import paddle_tpu.nn as nn

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


def _cbr(in_c: int, out_c: int, k: Any, stride: int = 1, padding: Any = 0) -> nn.Sequential:
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding, bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU(),
    )


def _cat(tensors: List[Any]) -> Any:
    import paddle_tpu as paddle

    return paddle.concat(tensors, axis=1)


class _Inception(nn.Layer):
    """GoogLeNet inception block: 1x1 / 3x3 / 5x5 / pool-proj branches."""

    def __init__(self, in_c: int, c1: int, c3r: int, c3: int, c5r: int, c5: int,
                 proj: int) -> None:
        super().__init__()
        self.b1 = _cbr(in_c, c1, 1)
        self.b3 = nn.Sequential(_cbr(in_c, c3r, 1), _cbr(c3r, c3, 3, padding=1))
        self.b5 = nn.Sequential(_cbr(in_c, c5r, 1), _cbr(c5r, c5, 5, padding=2))
        self.bp = nn.Sequential(nn.MaxPool2D(3, 1, padding=1), _cbr(in_c, proj, 1))

    def forward(self, x: Any) -> Any:
        return _cat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)])


class _AuxHead(nn.Layer):
    def __init__(self, in_c: int, num_classes: int) -> None:
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _cbr(in_c, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x: Any) -> Any:
        x = self.conv(self.pool(x)).flatten(1)
        return self.fc2(self.dropout(self.relu(self.fc1(x))))


class GoogLeNet(nn.Layer):
    """Reference ``googlenet.py``: returns ``(out, aux1, aux2)`` like the
    reference — aux heads hang off inception 4a/4d and train the weighted
    auxiliary losses; in eval they still compute (the reference returns them
    unconditionally too)."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True) -> None:
        super().__init__()
        self.stem = nn.Sequential(
            _cbr(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2, padding=1),
            _cbr(64, 64, 1), _cbr(64, 192, 3, padding=1), nn.MaxPool2D(3, 2, padding=1),
        )
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x: Any) -> Any:
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
            return x, aux1, aux2
        return x


class _InceptionA(nn.Layer):
    def __init__(self, in_c: int, pool_c: int) -> None:
        super().__init__()
        self.b1 = _cbr(in_c, 64, 1)
        self.b5 = nn.Sequential(_cbr(in_c, 48, 1), _cbr(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(
            _cbr(in_c, 64, 1), _cbr(64, 96, 3, padding=1), _cbr(96, 96, 3, padding=1)
        )
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _cbr(in_c, pool_c, 1))

    def forward(self, x: Any) -> Any:
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)])


class _InceptionB(nn.Layer):  # grid reduction
    def __init__(self, in_c: int) -> None:
        super().__init__()
        self.b3 = _cbr(in_c, 384, 3, stride=2)
        self.b33 = nn.Sequential(
            _cbr(in_c, 64, 1), _cbr(64, 96, 3, padding=1), _cbr(96, 96, 3, stride=2)
        )
        self.bp = nn.MaxPool2D(3, 2)

    def forward(self, x: Any) -> Any:
        return _cat([self.b3(x), self.b33(x), self.bp(x)])


class _InceptionC(nn.Layer):  # factorized 7x7
    def __init__(self, in_c: int, c7: int) -> None:
        super().__init__()
        self.b1 = _cbr(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _cbr(in_c, c7, 1), _cbr(c7, c7, (1, 7), padding=(0, 3)),
            _cbr(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.b77 = nn.Sequential(
            _cbr(in_c, c7, 1), _cbr(c7, c7, (7, 1), padding=(3, 0)),
            _cbr(c7, c7, (1, 7), padding=(0, 3)), _cbr(c7, c7, (7, 1), padding=(3, 0)),
            _cbr(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _cbr(in_c, 192, 1))

    def forward(self, x: Any) -> Any:
        return _cat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)])


class _InceptionD(nn.Layer):  # grid reduction
    def __init__(self, in_c: int) -> None:
        super().__init__()
        self.b3 = nn.Sequential(_cbr(in_c, 192, 1), _cbr(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _cbr(in_c, 192, 1), _cbr(192, 192, (1, 7), padding=(0, 3)),
            _cbr(192, 192, (7, 1), padding=(3, 0)), _cbr(192, 192, 3, stride=2),
        )
        self.bp = nn.MaxPool2D(3, 2)

    def forward(self, x: Any) -> Any:
        return _cat([self.b3(x), self.b7(x), self.bp(x)])


class _InceptionE(nn.Layer):  # expanded filter bank
    def __init__(self, in_c: int) -> None:
        super().__init__()
        self.b1 = _cbr(in_c, 320, 1)
        self.b3_stem = _cbr(in_c, 384, 1)
        self.b3_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.b33_stem = nn.Sequential(_cbr(in_c, 448, 1), _cbr(448, 384, 3, padding=1))
        self.b33_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b33_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _cbr(in_c, 192, 1))

    def forward(self, x: Any) -> Any:
        s = self.b3_stem(x)
        t = self.b33_stem(x)
        return _cat([
            self.b1(x), _cat([self.b3_a(s), self.b3_b(s)]),
            _cat([self.b33_a(t), self.b33_b(t)]), self.bp(x),
        ])


class InceptionV3(nn.Layer):
    """Reference ``inceptionv3.py``."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True) -> None:
        super().__init__()
        self.stem = nn.Sequential(
            _cbr(3, 32, 3, stride=2), _cbr(32, 32, 3), _cbr(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2), _cbr(64, 80, 1), _cbr(80, 192, 3), nn.MaxPool2D(3, 2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160), _InceptionC(768, 160),
            _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x: Any) -> Any:
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def googlenet(pretrained: bool = False, **kw: Any) -> GoogLeNet:
    return GoogLeNet(**kw)


def inception_v3(pretrained: bool = False, **kw: Any) -> InceptionV3:
    return InceptionV3(**kw)
