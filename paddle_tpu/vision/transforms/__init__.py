"""Vision transforms (reference ``python/paddle/vision/transforms``) — numpy
host-side preprocessing (runs in dataloader workers, off the TPU)."""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Compose",
    "ToTensor",
    "Normalize",
    "Resize",
    "CenterCrop",
    "RandomCrop",
    "RandomHorizontalFlip",
    "Transpose",
]


class Compose:
    def __init__(self, transforms: Sequence[Callable]) -> None:
        self.transforms = list(transforms)

    def __call__(self, data: Any) -> Any:
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    def __init__(self, data_format: str = "CHW") -> None:
        self.data_format = data_format

    def __call__(self, img: Any) -> Any:
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[..., None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        from paddle_tpu.core.tensor import Tensor

        return Tensor(arr)


class Normalize:
    def __init__(self, mean: Sequence[float], std: Sequence[float], data_format: str = "CHW", to_rgb: bool = False) -> None:
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img: Any) -> Any:
        arr = img.numpy() if hasattr(img, "numpy") else np.asarray(img, np.float32)
        if self.data_format == "CHW":
            arr = (arr - self.mean[:, None, None]) / self.std[:, None, None]
        else:
            arr = (arr - self.mean) / self.std
        from paddle_tpu.core.tensor import Tensor

        return Tensor(arr.astype(np.float32))


class Resize:
    def __init__(self, size: Any, interpolation: str = "bilinear") -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img: Any) -> Any:
        arr = np.asarray(img, np.float32)
        h, w = self.size
        ih, iw = arr.shape[:2]
        yi = (np.arange(h) * ih / h).astype(np.int64).clip(0, ih - 1)
        xi = (np.arange(w) * iw / w).astype(np.int64).clip(0, iw - 1)
        return arr[yi][:, xi]


class CenterCrop:
    def __init__(self, size: Any) -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img: Any) -> Any:
        arr = np.asarray(img)
        h, w = self.size
        ih, iw = arr.shape[:2]
        top = (ih - h) // 2
        left = (iw - w) // 2
        return arr[top : top + h, left : left + w]


class RandomCrop:
    def __init__(self, size: Any, padding: int = 0) -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img: Any) -> Any:
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = self.size
        ih, iw = arr.shape[:2]
        top = random.randint(0, ih - h)
        left = random.randint(0, iw - w)
        return arr[top : top + h, left : left + w]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5) -> None:
        self.prob = prob

    def __call__(self, img: Any) -> Any:
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order: Sequence[int] = (2, 0, 1)) -> None:
        self.order = tuple(order)

    def __call__(self, img: Any) -> Any:
        return np.asarray(img).transpose(self.order)
