"""Vision datasets (reference ``python/paddle/vision/datasets``):
DatasetFolder/ImageFolder directory pipelines + MNIST/Cifar file parsers.

Zero-egress environment: no downloads — datasets read from local files
(``download=False`` semantics); MNIST reads the idx byte format, Cifar the
pickled batch format, exactly like the reference parsers, so locally-provided
copies of the standard files work unchanged.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "MNIST", "FashionMNIST", "Cifar10", "Cifar100"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _load_image(path: str) -> np.ndarray:
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image  # optional dependency

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as exc:  # pragma: no cover - depends on image libs
        raise RuntimeError(
            f"loading {path} needs PIL; store arrays as .npy for a "
            "dependency-free pipeline"
        ) from exc


class DatasetFolder(Dataset):
    """``root/class_x/xxx.ext`` directory layout → (sample, class_index)
    (reference ``folder.py`` DatasetFolder)."""

    def __init__(
        self,
        root: str,
        loader: Optional[Callable] = None,
        extensions: Optional[Sequence[str]] = None,
        transform: Optional[Callable] = None,
        is_valid_file: Optional[Callable] = None,
    ) -> None:
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise RuntimeError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _dirs, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(dirpath, f)
                    ok = (
                        is_valid_file(path)
                        if is_valid_file is not None
                        else path.lower().endswith(exts)
                    )
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root} (extensions {exts})")

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, idx: int) -> Tuple[Any, int]:
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """Flat (unlabeled) image directory (reference ``folder.py`` ImageFolder)."""

    def __init__(
        self,
        root: str,
        loader: Optional[Callable] = None,
        extensions: Optional[Sequence[str]] = None,
        transform: Optional[Callable] = None,
        is_valid_file: Optional[Callable] = None,
    ) -> None:
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        self.samples: List[str] = []
        for dirpath, _dirs, files in sorted(os.walk(root)):
            for f in sorted(files):
                path = os.path.join(dirpath, f)
                ok = (
                    is_valid_file(path)
                    if is_valid_file is not None
                    else path.lower().endswith(exts)
                )
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root}")

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, idx: int) -> List[Any]:
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


def _read_idx(path: str) -> np.ndarray:
    """Parse the MNIST idx byte format (gz or raw)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """MNIST from local idx files (reference ``mnist.py``; no download)."""

    NAME = "mnist"

    def __init__(
        self,
        image_path: Optional[str] = None,
        label_path: Optional[str] = None,
        mode: str = "train",
        transform: Optional[Callable] = None,
        download: bool = False,
        backend: str = "cv2",
    ) -> None:
        if download:
            raise RuntimeError(
                f"{self.NAME}: no network egress — pass image_path/label_path "
                "to locally provided idx files"
            )
        if image_path is None or label_path is None:
            raise ValueError("image_path and label_path are required (no download)")
        self.images = _read_idx(image_path)
        self.labels = _read_idx(label_path).astype(np.int64)
        if len(self.images) != len(self.labels):
            raise ValueError(
                f"images ({len(self.images)}) and labels ({len(self.labels)}) disagree"
            )
        self.transform = transform
        self.mode = mode

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> Tuple[Any, np.ndarray]:
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from a local python-version archive dir or batch files
    (reference ``cifar.py``; no download)."""

    _train_files = [f"data_batch_{i}" for i in range(1, 6)]
    _test_files = ["test_batch"]
    _label_key = b"labels"

    def __init__(
        self,
        data_file: Optional[str] = None,
        mode: str = "train",
        transform: Optional[Callable] = None,
        download: bool = False,
        backend: str = "cv2",
    ) -> None:
        if download:
            raise RuntimeError("no network egress — pass data_file to a local copy")
        if data_file is None:
            raise ValueError("data_file is required (no download)")
        names = self._train_files if mode == "train" else self._test_files
        images, labels = [], []
        for n in names:
            path = os.path.join(data_file, n) if os.path.isdir(data_file) else data_file
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            images.append(np.asarray(batch[b"data"], np.uint8))
            labels.extend(batch[self._label_key])
            if not os.path.isdir(data_file):
                break
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform
        self.mode = mode

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> Tuple[Any, np.ndarray]:
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    _train_files = ["train"]
    _test_files = ["test"]
    _label_key = b"fine_labels"
