"""Serving-layer metric families (SLO/overload observability).

The serving front end (`paddle_tpu/serving/`) reports through these; they
live here so the whole metric surface — engine, collectives, jit, serving —
is defined against ONE registry with one naming convention, and so exporters
and dashboards can discover them without importing the serving stack.

Conventions:

- ``priority`` label values are the class names (``interactive`` /
  ``standard`` / ``best_effort``; unknown numeric classes render as their
  number) so a Prometheus query never needs the enum;
- every shed path — bounded-queue rejection, overload rejection, deadline
  expiry at either lifecycle stage, client disconnect, engine failure —
  accounts into ``serving_shed_total{reason}``: the sum over reasons equals
  the number of requests that entered the frontend (or tried to) and did not
  finish normally. Deadline sheds ALSO count into
  ``serving_deadline_miss_total{stage}`` with the lifecycle stage
  (``queued`` vs ``decode``) the deadline caught them in.
"""

from __future__ import annotations

from typing import Any, Dict

from paddle_tpu.observability import metrics as _metrics

__all__ = ["PRIORITY_NAMES", "priority_name", "router_metrics", "serving_metrics"]

# canonical priority classes (lower = more important); the serving layer
# re-exports these as Priority.INTERACTIVE / STANDARD / BEST_EFFORT
PRIORITY_NAMES: Dict[int, str] = {0: "interactive", 1: "standard", 2: "best_effort"}


def priority_name(priority: int) -> str:
    return PRIORITY_NAMES.get(int(priority), str(int(priority)))


def serving_metrics() -> Dict[str, Any]:
    """Get-or-create the serving metric families (process-global, like the
    engine's `_engine_metrics`)."""
    reg = _metrics.GLOBAL_METRICS
    return {
        "requests": reg.counter(
            "serving_requests_total",
            "Requests accepted by the serving frontend, by tenant and priority.",
            labelnames=("tenant", "priority"),
        ),
        "shed": reg.counter(
            "serving_shed_total",
            "Requests shed instead of served, by reason (queue_full / overload "
            "/ deadline_queued / deadline_decode / client_disconnect / "
            "stream_timeout / engine_failure / cancelled).",
            labelnames=("reason",),
        ),
        "deadline_miss": reg.counter(
            "serving_deadline_miss_total",
            "Requests whose deadline expired, by the lifecycle stage the "
            "expiry caught them in (queued: shed before prefill; decode: "
            "evicted mid-generation, blocks reclaimed).",
            labelnames=("stage",),
        ),
        "degraded": reg.counter(
            "serving_degraded_total",
            "Graceful-degradation actions taken under pressure, by action "
            "(clamp_max_new_tokens).",
            labelnames=("action",),
        ),
        "queue_wait": reg.histogram(
            "serving_queue_wait_seconds",
            "Time from frontend accept to engine admission (prefill start), "
            "per priority class.",
            labelnames=("priority",),
        ),
        "ttft": reg.histogram(
            "serving_ttft_seconds",
            "Time from frontend accept to the first streamed token, per "
            "priority class.",
            labelnames=("priority",),
        ),
        "tokens": reg.counter(
            "serving_tokens_total",
            "Tokens streamed to clients, per priority class.",
            labelnames=("priority",),
        ),
        "goodput": reg.counter(
            "serving_goodput_tokens_total",
            "Tokens of requests that finished normally INSIDE their SLO "
            "deadline (the metric an overloaded deployment lives on), per "
            "priority class.",
            labelnames=("priority",),
        ),
        "queue_depth": reg.gauge(
            "serving_queue_depth",
            "Requests waiting in the frontend's bounded intake queue.",
        ),
        "level": reg.gauge(
            "serving_overload_level",
            "Overload controller state: 0 normal, 1 degraded (best-effort "
            "budgets clamped), 2 shedding (low-priority intake rejected). "
            "High-water mark tracked since reset.",
        ),
        "responses": reg.counter(
            "serving_http_responses_total",
            "HTTP responses by status code (200/400/404/429/500).",
            labelnames=("code",),
        ),
        "prefix_hit_rate": reg.gauge(
            "serving_prefix_cache_hit_rate",
            "Engine prefix-cache hit rate (admissions that reused cached "
            "prefix KV / all admissions) since engine construction; 0 when "
            "the prefix cache is disabled.",
        ),
    }


def router_metrics() -> Dict[str, Any]:
    """Get-or-create the cluster-router metric families. The ``route``
    counter is the reconciliation surface: every routing decision — initial
    dispatch or failover re-dispatch — increments exactly one
    ``{route}`` cell (``affinity`` / ``spill`` / ``failover`` /
    ``round_robin``), so the sum over routes equals the number of dispatches
    the routing log records. Router-originated sheds (``replica_failure``,
    deadline at failover, ``no_replicas``) account into the shared
    ``serving_shed_total{reason}`` family — replica-frontend sheds are
    already counted there by the frontends themselves."""
    reg = _metrics.GLOBAL_METRICS
    return {
        "route": reg.counter(
            "serving_router_route_total",
            "Routing decisions by kind: affinity (prefix-hash target), spill "
            "(affinity target shedding/full -> least-loaded healthy replica), "
            "failover (re-dispatch off a dead/failed replica), round_robin "
            "(the A/B baseline policy).",
            labelnames=("route",),
        ),
        "replica_state": reg.gauge(
            "serving_router_replica_state",
            "Replica health state per replica: 0 up, 1 degraded, 2 draining, "
            "3 dead. High-water mark tracked since reset.",
            labelnames=("replica",),
        ),
        "routable": reg.gauge(
            "serving_router_routable_replicas",
            "Replicas currently accepting routed intake (UP or DEGRADED).",
        ),
        "redispatch": reg.counter(
            "serving_router_redispatch_total",
            "Re-dispatch attempts scheduled off dead replicas (bounded per "
            "request by the router's max_redispatch budget).",
        ),
        "salvaged": reg.counter(
            "serving_router_salvaged_total",
            "Requests whose results were salvaged from a dead replica's "
            "drain_finished() buffer and delivered instead of re-dispatched.",
        ),
        "failover_latency": reg.histogram(
            "serving_router_failover_seconds",
            "Replica death detection -> the victim request re-accepted on a "
            "healthy replica.",
        ),
    }
