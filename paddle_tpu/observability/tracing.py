"""Per-request distributed tracing: span trees over the serving lifecycle.

Aggregate histograms (PR 2) say *that* p99 decode latency moved; they cannot
say where ONE request's latency went — and in a continuous-batching engine
that question is entangled by design: a request's decode time is a share of
batched steps it rode with strangers ("Ragged Paged Attention", PAPERS.md,
serves exactly such mixed batches). This module provides real span trees,
mirroring the reference fork's profiler layer (SURVEY §5.1: chrome-trace
export, ``RecordEvent`` spans):

- **spans** carry ``trace_id`` / ``span_id`` / ``parent_id`` links, so the
  queue → prefill → decode → stream phases of one request nest under one
  root and sum to its end-to-end latency;
- **head sampling** is seeded: the sampling decision and every generated id
  come from one ``random.Random(FLAGS_trace_seed)``, so a given seed +
  request sequence reproduces the same traces (replayable investigations,
  deterministic tests). ``FLAGS_trace_sample_rate`` is the probability; an
  incoming ``traceparent`` header's sampled flag overrides the coin, so a
  caller's sampling decision propagates through this hop;
- **zero cost when off**: ``tracing_enabled()`` is one cached-bool list
  read (the same flag-listener gate as the metrics layer). Rate 0 means no
  rng draw, no id generation, no store append — nothing;
- **bounded store**: completed spans land in a ``deque(maxlen=...)`` ring —
  a tracer left on for days cannot grow host memory; the newest spans win
  and ``dropped`` counts what the ring evicted;
- **export**: JSONL (one span per line — the flight-recorder CLI converts
  it) and chrome-trace ``traceEvents``; ``profiler.Profiler.export`` drains
  :func:`Tracer.drain_chrome_events` into its existing span stream, so
  request spans land on the same perf_counter timeline as ``RecordEvent``
  spans and metrics-snapshot instants. Exports declare the
  ``tracing.export`` fault site: a failing export must never take down the
  path that called it (callers use the ``safe_*`` forms on failure seams).

The ``traceparent`` header follows the W3C shape
``00-<32 hex trace_id>-<16 hex span_id>-<2 hex flags>`` (flag bit 0x01 =
sampled); malformed headers are ignored and a fresh trace starts.
"""

from __future__ import annotations

import json
import os
import random
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Union

from paddle_tpu.flags import GLOBAL_FLAGS

__all__ = [
    "GLOBAL_TRACER",
    "Span",
    "TraceContext",
    "Tracer",
    "format_traceparent",
    "get_tracer",
    "parse_traceparent",
    "tracing_enabled",
    "tracing_full",
]

# cached FLAGS_trace_sample_rate: one list read on the off path; the listener
# keeps all three cells in lockstep with set_flags / env seeding
_ENABLED = [False]
_FULL = [False]
_RATE = [0.0]


def _refresh_rate(value: Any) -> None:
    rate = float(value)
    _RATE[0] = rate
    _ENABLED[0] = rate > 0.0
    _FULL[0] = rate >= 1.0


GLOBAL_FLAGS.on_change("trace_sample_rate", _refresh_rate)
_refresh_rate(GLOBAL_FLAGS.get("trace_sample_rate"))  # seeds FLAGS_ env var


def tracing_enabled() -> bool:
    """Current ``FLAGS_trace_sample_rate > 0`` without touching the flag
    registry — the one gate every instrumentation site checks first."""
    return _ENABLED[0]


def tracing_full() -> bool:
    """Current ``FLAGS_trace_sample_rate >= 1`` (same cached-cell cost).
    The gate for spans with NO request context to sample against (e.g. the
    collective wrappers): at a partial rate, emitting every such call would
    flood the bounded ring and evict the rare sampled request trees the
    rate was chosen to capture."""
    return _FULL[0]


_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


class TraceContext:
    """Propagatable identity of one span: where new children attach.

    ``span_id`` is THIS context's span (children parent to it);
    ``parent_id`` is the remote parent from an incoming traceparent hop, if
    any. ``sampled`` is the head-sampling decision — unsampled contexts
    still carry ids so the trace id propagates across hops unbroken."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str] = None,
        sampled: bool = True,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def __repr__(self) -> str:
        return (
            f"TraceContext(trace_id={self.trace_id!r}, span_id={self.span_id!r}, "
            f"parent_id={self.parent_id!r}, sampled={self.sampled})"
        )


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` header; malformed/absent -> None (the caller
    starts a fresh trace — a bad header must never fail a request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    _, trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # all-zero ids are invalid per the header spec
    return TraceContext(trace_id, span_id, None, sampled=bool(int(flags, 16) & 1))


def format_traceparent(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


class Span:
    """One in-flight span; a context manager (the ONLY sanctioned open form —
    analyzer check OB601 flags a ``tracer.span(...)`` not under ``with``,
    because an unclosed span never reaches the store and leaks silently).
    Unsampled spans go through the same protocol but record nothing."""

    __slots__ = (
        "_tracer", "name", "trace_id", "span_id", "parent_id",
        "attrs", "sampled", "_start_s",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        attrs: Optional[Dict[str, Any]],
        sampled: bool,
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.sampled = sampled
        self._start_s: float = 0.0

    def set_attr(self, key: str, value: Any) -> None:
        if self.sampled:
            self.attrs[key] = value

    def context(self) -> TraceContext:
        """Attachment point for children of this span."""
        return TraceContext(self.trace_id, self.span_id, self.parent_id, self.sampled)

    def __enter__(self) -> "Span":
        self._start_s = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        if not self.sampled:
            return
        status = "ok" if exc_type is None else f"error:{exc_type.__name__}"
        self._tracer.add_span(
            self.name,
            trace_id=self.trace_id,
            span_id=self.span_id,
            parent_id=self.parent_id,
            start_s=self._start_s,
            end_s=time.perf_counter(),
            attrs=self.attrs,
            status=status,
        )


class Tracer:
    """Seeded span factory over a bounded in-process store.

    All id generation and sampling coins come from one private
    ``random.Random(seed)`` under the tracer lock: given the same seed and
    the same sequence of :meth:`start_trace` / :meth:`span` calls, the
    emitted ids and sampling decisions are identical."""

    def __init__(
        self, capacity: Optional[int] = None, seed: Optional[int] = None
    ) -> None:
        cap = int(
            GLOBAL_FLAGS.get("trace_buffer_size") if capacity is None else capacity
        )
        if cap < 1:
            raise ValueError(f"trace buffer capacity must be >= 1, got {cap}")
        self._lock = threading.Lock()
        self._store: deque = deque(maxlen=cap)
        self._rng = random.Random(
            int(GLOBAL_FLAGS.get("trace_seed")) if seed is None else int(seed)
        )
        self.dropped = 0  # spans evicted by the bounded ring

    # -- identity / sampling -------------------------------------------------
    def reseed(self, seed: int) -> None:
        with self._lock:
            self._rng = random.Random(int(seed))

    def _gen_id(self, nbytes: int) -> str:
        return f"{self._rng.getrandbits(nbytes * 8):0{nbytes * 2}x}"

    def start_trace(
        self,
        traceparent: Optional[str] = None,
        sample_rate: Optional[float] = None,
    ) -> TraceContext:
        """Head-sampling decision for one request; returns the request's
        ROOT context (fresh ``span_id``; record the root span against it).
        An incoming traceparent pins the trace id AND the sampling decision
        (the upstream hop already flipped the coin); otherwise one seeded
        coin against the rate decides."""
        parent = parse_traceparent(traceparent)
        with self._lock:
            if parent is not None:
                return TraceContext(
                    parent.trace_id, self._gen_id(8), parent.span_id, parent.sampled
                )
            rate = _RATE[0] if sample_rate is None else float(sample_rate)
            sampled = rate > 0.0 and self._rng.random() < rate
            return TraceContext(self._gen_id(16), self._gen_id(8), None, sampled)

    # -- recording -----------------------------------------------------------
    def span(
        self,
        name: str,
        parent: Optional[Union[TraceContext, Span]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        """Open one live span (use ONLY as ``with tracer.span(...) as sp:`` —
        analyzer check OB601). ``parent=None`` starts a fresh single-span
        trace (engine batch steps, collectives); an unsampled parent yields
        a no-op span."""
        if isinstance(parent, Span):
            parent = parent.context()
        if parent is None:
            with self._lock:
                trace_id, span_id = self._gen_id(16), self._gen_id(8)
            return Span(self, name, trace_id, span_id, None, attrs, True)
        with self._lock:
            span_id = self._gen_id(8)
        return Span(
            self, name, parent.trace_id, span_id, parent.span_id, attrs,
            parent.sampled,
        )

    def add_span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        start_s: float = 0.0,
        end_s: float = 0.0,
        attrs: Optional[Dict[str, Any]] = None,
        status: str = "ok",
    ) -> str:
        """Record one completed span from timestamps the caller already holds
        (how the serving frontend emits a request's phase spans at terminal
        time — no live span object rides the hot path). Returns the span id."""
        with self._lock:
            if trace_id is None:
                trace_id = self._gen_id(16)
            if span_id is None:
                span_id = self._gen_id(8)
            if len(self._store) == self._store.maxlen:
                self.dropped += 1
            self._store.append(
                {
                    "kind": "span",
                    "name": name,
                    "trace_id": trace_id,
                    "span_id": span_id,
                    "parent_id": parent_id,
                    "ts_us": start_s * 1e6,
                    "dur_us": max(0.0, (end_s - start_s) * 1e6),
                    "status": status,
                    "attrs": dict(attrs) if attrs else {},
                }
            )
        return span_id

    def add_event(
        self,
        name: str,
        ctx: Optional[TraceContext] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record one instant event (chrome ``ph:"i"``) — compile events,
        stream-out chunk marks. Unsampled context -> no-op."""
        if ctx is not None and not ctx.sampled:
            return
        with self._lock:
            if len(self._store) == self._store.maxlen:
                self.dropped += 1
            self._store.append(
                {
                    "kind": "event",
                    "name": name,
                    "trace_id": ctx.trace_id if ctx is not None else None,
                    "parent_id": ctx.span_id if ctx is not None else None,
                    "ts_us": time.perf_counter() * 1e6,
                    "attrs": dict(attrs) if attrs else {},
                }
            )

    # -- read / export -------------------------------------------------------
    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of the bounded store (spans + instant events), oldest
        first; does not drain."""
        with self._lock:
            return [dict(r) for r in self._store]

    def spans(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        return [
            r
            for r in self.records()
            if r["kind"] == "span"
            and (trace_id is None or r["trace_id"] == trace_id)
        ]

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._store = list(self._store), deque(maxlen=self._store.maxlen)
        return out

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self.dropped = 0

    @staticmethod
    def _to_chrome(rec: Dict[str, Any]) -> Dict[str, Any]:
        args = dict(rec.get("attrs") or {})
        for k in ("trace_id", "span_id", "parent_id", "status"):
            if rec.get(k) is not None:
                args[k] = rec[k]
        ev: Dict[str, Any] = {
            "name": rec["name"],
            "ts": rec["ts_us"],
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args,
        }
        if rec["kind"] == "event":
            ev["ph"], ev["s"] = "i", "t"
        else:
            ev["ph"], ev["dur"] = "X", rec["dur_us"]
        return ev

    def drain_chrome_events(self) -> List[Dict[str, Any]]:
        """Drain the store as chrome traceEvents — what
        ``profiler.Profiler.export`` merges into its span stream."""
        return [self._to_chrome(r) for r in self.drain()]

    def export_jsonl(self, path: str) -> int:
        """Append every stored record to ``path``, one JSON object per line
        (the dump CLI converts this to a chrome trace); returns the record
        count. Does not drain. Declares the ``tracing.export`` fault site."""
        from paddle_tpu.testing.faults import fault_point  # lazy: import cycle

        fault_point("tracing.export")
        records = self.records()
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return len(records)

    def export_chrome(self, path: str) -> int:
        """Write the store as a chrome trace JSON (non-draining)."""
        from paddle_tpu.testing.faults import fault_point  # lazy: import cycle

        fault_point("tracing.export")
        records = self.records()
        with open(path, "w") as f:
            json.dump({"traceEvents": [self._to_chrome(r) for r in records]}, f)
        return len(records)

    def safe_export_jsonl(self, path: str) -> Optional[int]:
        """Export that never raises — the form failure seams (pump death,
        engine failure) use: a broken disk or an injected ``tracing.export``
        fault must not take down the path being post-mortemed."""
        try:
            return self.export_jsonl(path)
        except Exception:  # export is best-effort by contract on failure seams
            return None


GLOBAL_TRACER = Tracer()


def get_tracer() -> Tracer:
    return GLOBAL_TRACER


def _reseed_global(value: Any) -> None:
    GLOBAL_TRACER.reseed(int(value))


GLOBAL_FLAGS.on_change("trace_seed", _reseed_global)
