"""Device-time attribution: per-step cost profiles, host-bubble analysis,
and a measured comm/compute breakdown.

Every observability layer to date (metrics, tracing, fleet/SLO) stops at the
dispatch boundary: it knows when a step was *launched* and when its result
was *consumed*, but nothing attributes time below that line — which kernel
categories dominate, how much of a step is host bubble, or what the
collectives actually cost. This module closes that gap in three pieces:

- **Static cost profiles.** On every compile the recompile watchdog (fed a
  ``cost_thunk`` by its call sites) captures ``compiled.cost_analysis()`` —
  flops, HBM bytes — keyed by the watchdog's signature, so each compiled
  program carries a cost model. The thunk is an *introspective AOT
  lowering* (``fn.lower(...).compile()``): it re-runs the Python trace and
  pays one extra XLA compile, which is why capture arms only while
  ``FLAGS_devprof_sample_rate > 0`` — compile seams are seconds-scale
  already, but doubling them must be opt-in. jax 0.4.x returns a dict, a
  list of per-computation dicts, or raises depending on backend; the shim
  normalizes all three (missing backends record ``cost_model:
  "unavailable"`` with zeroed numbers rather than raising, so the CPU tier
  exercises the full path). A **cost-regression ledger** compares each new
  signature's flops/bytes against the function's previous program and flags
  drift past a tolerance — a re-trace that silently changed the program's
  cost is exactly the regression a recompile count alone cannot see.

- **Sampled step profiles.** Behind ``FLAGS_devprof_sample_rate`` (the same
  listener-cached-bool off-path as metrics/tracing: rate 0 costs one list
  read, and sampling is a deterministic stride — no RNG draw, so profiling
  can never perturb seeded reproducibility). A sampled engine step is timed
  device-sync-honest from four instants (step start, dispatch call,
  dispatch return, sync complete) and decomposed into **host-prep /
  dispatch-gap (bubble) / device** segments that tile the step wall
  exactly. Device time is apportioned across **attention / matmul /
  collective / other** categories using the cost profile as the attribution
  prior (caveat: apportionment, not per-kernel measurement — the prior is
  an analytic flop/byte split reconciled against the XLA cost model).
  Profiles land in share histograms, a bounded per-engine step-timeline
  ring (``FLAGS_devprof_timeline_size``), ``devprof_step`` flight-recorder
  events (so postmortem dumps carry them), and chrome-trace counter tracks
  merged by ``profiler.Profiler.export``.

- **Measured comm share.** While a sampled step is in flight the engine
  arms a thread-local comm window; the instrumented collective wrapper
  (``distributed/collective.py``) feeds its per-op host timings into it.
  When the window caught real wrapper time, the step's collective share is
  measured (``comm_source: "wrapper"``); when the program's collectives are
  GSPMD-inserted (the tp engine's all-reduces — invisible to host
  wrappers), the share falls back to the cost-model prior (``comm_source:
  "cost_model"``) applied to the *measured* device segment. ``bench.py``
  reports this as ``comm_share_measured`` next to the analytic estimate
  (now labeled ``comm_share_analytic``) plus ``host_bubble_fraction``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.flags import GLOBAL_FLAGS

from . import flight_recorder as _flight
from . import metrics as _metrics

__all__ = [
    "CostLedger",
    "GLOBAL_COST_LEDGER",
    "SampleGate",
    "StepTimeline",
    "begin_comm_window",
    "capture_cost_profile",
    "comm_window_armed",
    "devprof_enabled",
    "drain_chrome_events",
    "end_comm_window",
    "normalize_cost_analysis",
    "record_comm",
    "record_step_profile",
    "summarize_timeline",
]

CATEGORIES = ("attention", "matmul", "collective", "other")

# cached FLAGS_devprof_sample_rate: one list read on the off path; the
# listener keeps both cells in lockstep with set_flags / env seeding
_ENABLED = [False]
_RATE = [0.0]


def _refresh_rate(value: Any) -> None:
    rate = float(value)
    _RATE[0] = rate
    _ENABLED[0] = rate > 0.0


GLOBAL_FLAGS.on_change("devprof_sample_rate", _refresh_rate)
_refresh_rate(GLOBAL_FLAGS.get("devprof_sample_rate"))  # seeds FLAGS_ env var


def devprof_enabled() -> bool:
    """Current ``FLAGS_devprof_sample_rate > 0`` without touching the flag
    registry — the one gate every profiling site checks first."""
    return _ENABLED[0]


# -- metric families ----------------------------------------------------------
_share_hist = _metrics.GLOBAL_METRICS.histogram(
    "devprof_category_share",
    "Per-category share of a sampled step's device segment (attribution by "
    "the compile-time cost prior; shares sum to 1 per sampled step).",
    labelnames=("category",),
)
_bubble_hist = _metrics.GLOBAL_METRICS.histogram(
    "devprof_host_bubble_fraction",
    "Host fraction of a sampled step's wall (host-prep + dispatch-gap over "
    "the device-sync-honest step wall).",
)
_device_hist = _metrics.GLOBAL_METRICS.histogram(
    "devprof_device_seconds",
    "Device segment (dispatch-return to sync-complete) of sampled steps.",
)
_regression_counter = _metrics.GLOBAL_METRICS.counter(
    "devprof_cost_regressions_total",
    "Cost-regression ledger entries: a re-trace whose flops/bytes drifted "
    "from the function's previous compiled program.",
)


# -- cost_analysis shims ------------------------------------------------------

_COST_KEYS = {"flops": "flops", "bytes accessed": "bytes_accessed",
              "transcendentals": "transcendentals"}


def normalize_cost_analysis(raw: Any) -> Dict[str, Any]:
    """Normalize ``compiled.cost_analysis()`` output across jax versions:
    a dict, a list of per-computation dicts (summed), or None/garbage —
    the last records ``cost_model: "unavailable"`` with zeroed numbers
    instead of raising, so backends without an XLA cost model (CPU in some
    builds) still exercise the full capture path."""
    dicts: List[Dict[str, Any]] = []
    if isinstance(raw, dict):
        dicts = [raw]
    elif isinstance(raw, (list, tuple)):
        dicts = [d for d in raw if isinstance(d, dict)]
    out: Dict[str, Any] = {k: 0.0 for k in _COST_KEYS.values()}
    seen_any = False
    for d in dicts:
        for src, dst in _COST_KEYS.items():
            v = d.get(src)
            if isinstance(v, (int, float)):
                out[dst] += float(v)
                seen_any = True
    out["cost_model"] = "xla" if seen_any else "unavailable"
    return out


def _category_prior(
    profile: Dict[str, Any], hints: Optional[Dict[str, float]]
) -> Dict[str, float]:
    """Normalized attribution prior over :data:`CATEGORIES`. ``hints`` are
    analytic per-category weights from the capturing component (comparable
    units — estimated seconds or flops); the XLA cost model reconciles the
    tail: measured flops beyond the analytic attention+matmul total land in
    "other" (fused epilogues, bookkeeping ops the analytic split ignores).
    Without hints everything is "other" — an honest "unattributed"."""
    weights = {k: 0.0 for k in CATEGORIES}
    if hints:
        for k in CATEGORIES:
            v = hints.get(k)
            if isinstance(v, (int, float)) and v > 0:
                weights[k] = float(v)
    known = weights["attention"] + weights["matmul"]
    xla_flops = float(profile.get("flops") or 0.0)
    if known > 0 and xla_flops > known:
        # hints are flop-denominated when attention/matmul came from flop
        # counts; the excess the cost model measured is real device work
        # the analytic split has no name for
        weights["other"] += xla_flops - known
    total = sum(weights.values())
    if total <= 0:
        return {"attention": 0.0, "matmul": 0.0, "collective": 0.0, "other": 1.0}
    return {k: v / total for k, v in weights.items()}


# -- cost-regression ledger ---------------------------------------------------

class CostLedger:
    """Per-(fn, signature) cost profiles with fn-level drift detection.

    ``record`` compares each new profile against the SAME function's
    previously recorded program (any signature): a shape-bucket re-trace
    that moved flops/bytes past ``drift_tolerance`` (relative) appends a
    regression entry, bumps ``devprof_cost_regressions_total`` and drops a
    ``cost_regression`` line into the flight ring — compile-time truth the
    postmortem can line up against the latency timeline."""

    def __init__(self, drift_tolerance: float = 0.01) -> None:
        self._lock = threading.Lock()
        self.drift_tolerance = float(drift_tolerance)
        # fn -> {signature: profile}; insertion order = capture order
        self._profiles: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._latest: Dict[str, tuple] = {}  # fn -> (signature, profile)
        self.regressions: List[Dict[str, Any]] = []

    @staticmethod
    def _drift(prev: float, new: float) -> float:
        if prev == 0.0:
            return 0.0 if new == 0.0 else float("inf")
        return abs(new - prev) / abs(prev)

    def record(self, fn: str, signature: str, profile: Dict[str, Any]) -> None:
        sig = str(signature)[:200]
        with self._lock:
            prev = self._latest.get(fn)
            self._profiles.setdefault(fn, {})[sig] = dict(profile)
            self._latest[fn] = (sig, dict(profile))
        if prev is None or prev[0] == sig:
            return
        prev_sig, prev_prof = prev
        if (
            prev_prof.get("cost_model") == "unavailable"
            or profile.get("cost_model") == "unavailable"
        ):
            return  # no numbers on one side: drift is undefined, not zero
        drift_flops = self._drift(
            float(prev_prof.get("flops") or 0.0), float(profile.get("flops") or 0.0)
        )
        drift_bytes = self._drift(
            float(prev_prof.get("bytes_accessed") or 0.0),
            float(profile.get("bytes_accessed") or 0.0),
        )
        if max(drift_flops, drift_bytes) <= self.drift_tolerance:
            return
        entry = {
            "fn": fn,
            "prev_signature": prev_sig,
            "signature": sig,
            "prev_flops": prev_prof.get("flops"),
            "flops": profile.get("flops"),
            "prev_bytes": prev_prof.get("bytes_accessed"),
            "bytes": profile.get("bytes_accessed"),
            "drift_flops": drift_flops,
            "drift_bytes": drift_bytes,
        }
        with self._lock:
            self.regressions.append(entry)
        _regression_counter.inc()
        _flight.record_event(
            "cost_regression", fn=fn, signature=sig,
            drift_flops=round(drift_flops, 4), drift_bytes=round(drift_bytes, 4),
        )

    def profile_for(self, fn: str, signature: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            by_sig = self._profiles.get(fn)
            if not by_sig:
                return None
            prof = by_sig.get(str(signature)[:200])
            if prof is None:
                # an unknown signature still gets the fn's latest profile:
                # better a slightly stale prior than no attribution at all
                prof = self._latest[fn][1]
            return dict(prof)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "profiles": {
                    fn: {sig: dict(p) for sig, p in by_sig.items()}
                    for fn, by_sig in self._profiles.items()
                },
                "regressions": [dict(r) for r in self.regressions],
            }

    def reset(self) -> None:
        with self._lock:
            self._profiles.clear()
            self._latest.clear()
            self.regressions.clear()


GLOBAL_COST_LEDGER = CostLedger()


def capture_cost_profile(
    fn: str,
    signature: str,
    cost_thunk: Callable[[], Any],
    hints: Optional[Dict[str, float]] = None,
) -> Optional[Dict[str, Any]]:
    """Run one compile seam's cost capture into the global ledger. No-op at
    rate 0; never raises — a broken cost model must not fail the compile
    path it is documenting. Returns the normalized profile (or None)."""
    if not _ENABLED[0]:
        return None
    try:
        raw = cost_thunk()
    except Exception:  # noqa: BLE001 - cost capture is best-effort by contract
        raw = None
    profile = normalize_cost_analysis(raw)
    profile["categories"] = _category_prior(profile, hints)
    GLOBAL_COST_LEDGER.record(fn, signature, profile)
    return profile


# -- sampling -----------------------------------------------------------------

class SampleGate:
    """Deterministic stride sampler: at rate r, every round(1/r)-th call
    samples (rate >= 1 samples every call). No RNG — profiling a seeded run
    cannot perturb its reproducibility, and the off path is one list read."""

    def __init__(self) -> None:
        self._n = 0

    def should_sample(self) -> bool:
        if not _ENABLED[0]:
            return False
        rate = _RATE[0]
        self._n += 1
        if rate >= 1.0:
            return True
        stride = max(1, int(round(1.0 / rate)))
        return (self._n - 1) % stride == 0


# -- per-step comm window -----------------------------------------------------
# threading.local, not a global: each engine's pump thread arms its own
# window, so concurrently stepping replicas never cross-contaminate
class _CommWindow(threading.local):
    ops: Optional[Dict[str, float]] = None


_WIN = _CommWindow()


def comm_window_armed() -> bool:
    return _WIN.ops is not None


def begin_comm_window() -> None:
    _WIN.ops = {}


def end_comm_window() -> Dict[str, float]:
    ops, _WIN.ops = _WIN.ops, None
    return ops or {}


def record_comm(op: str, seconds: float) -> None:
    """Fed by the instrumented collective wrapper while a window is armed."""
    ops = _WIN.ops
    if ops is not None:
        ops[op] = ops.get(op, 0.0) + float(seconds)


# -- step timeline ring -------------------------------------------------------

class StepTimeline:
    """Bounded per-engine ring of sampled step profiles (newest win)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        cap = int(
            GLOBAL_FLAGS.get("devprof_timeline_size")
            if capacity is None
            else capacity
        )
        if cap < 1:
            raise ValueError(f"timeline capacity must be >= 1, got {cap}")
        self._store: deque = deque(maxlen=cap)
        self._lock = threading.Lock()

    def append(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._store.append(entry)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._store]

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()


# chrome counter-track buffer drained by profiler.Profiler.export; bounded so
# an exporter that never runs cannot grow host memory
_CHROME_EVENTS: deque = deque(maxlen=4096)
_CHROME_LOCK = threading.Lock()


def record_step_profile(
    fn: str,
    signature: str,
    t0: float,
    call_s: float,
    ret_s: float,
    sync_s: float,
    comm_ops: Optional[Dict[str, float]] = None,
    n_active: int = 0,
    step: int = 0,
    timeline: Optional[StepTimeline] = None,
    flight: Optional[Any] = None,
) -> Dict[str, Any]:
    """Assemble one sampled step's profile from its four timing instants.

    The segments are consecutive differences of the same ``perf_counter``
    readings, so host_prep + dispatch + device tiles the wall EXACTLY —
    the honesty property the devprof test pins. Device time is apportioned
    across categories by the cost prior; wrapper-measured collective time
    overrides the prior's collective share when the window caught any."""
    wall = max(sync_s - t0, 0.0)
    host_prep = max(call_s - t0, 0.0)
    dispatch = max(ret_s - call_s, 0.0)
    device = max(sync_s - ret_s, 0.0)
    prof = GLOBAL_COST_LEDGER.profile_for(fn, signature)
    prior = (
        dict(prof["categories"])
        if prof and isinstance(prof.get("categories"), dict)
        else {"attention": 0.0, "matmul": 0.0, "collective": 0.0, "other": 1.0}
    )
    comm_s = sum((comm_ops or {}).values())
    if comm_s > 0.0 and device > 0.0:
        # the wrapper measured real collective host time inside the window:
        # its share of the device segment is measurement, not prior — the
        # non-collective categories split the remainder by their prior ratio
        coll = min(comm_s / device, 1.0)
        rest_prior = sum(v for k, v in prior.items() if k != "collective")
        shares = {
            k: ((1.0 - coll) * (v / rest_prior) if rest_prior > 0 else 0.0)
            for k, v in prior.items()
            if k != "collective"
        }
        shares["collective"] = coll
        if rest_prior <= 0:
            shares["other"] = 1.0 - coll
        comm_source = "wrapper"
    else:
        shares = prior
        comm_source = (
            "cost_model" if prior.get("collective", 0.0) > 0.0 else "none"
        )
    total = sum(shares.values())
    if total > 0:
        shares = {k: v / total for k, v in shares.items()}
    entry = {
        "t_s": t0,
        "step": int(step),
        "n_active": int(n_active),
        "wall_s": wall,
        "host_prep_s": host_prep,
        "dispatch_s": dispatch,
        "device_s": device,
        "host_bubble_fraction": ((host_prep + dispatch) / wall) if wall > 0 else 0.0,
        "comm_s": comm_s,
        "comm_source": comm_source,
        "categories": {k: round(v, 6) for k, v in shares.items()},
        "cost_model": (prof or {}).get("cost_model", "missing"),
        "signature": str(signature)[:200],
    }
    if timeline is not None:
        timeline.append(entry)
    if flight is not None:
        flight.record(
            "devprof_step",
            step=entry["step"], n_active=entry["n_active"],
            wall_ms=round(wall * 1e3, 4),
            host_prep_ms=round(host_prep * 1e3, 4),
            dispatch_ms=round(dispatch * 1e3, 4),
            device_ms=round(device * 1e3, 4),
            host_bubble_fraction=round(entry["host_bubble_fraction"], 4),
            comm_source=comm_source,
            categories=entry["categories"],
        )
    if _metrics.metrics_enabled():
        for k, v in shares.items():
            _share_hist.labels(category=k).observe(v)
        _bubble_hist.observe(entry["host_bubble_fraction"])
        _device_hist.observe(device)
    with _CHROME_LOCK:
        ts_us = t0 * 1e6
        # counter tracks: device ms per category, plus the segment split —
        # Profiler.export merges these onto the RecordEvent/span timeline
        _CHROME_EVENTS.append(
            {
                "name": "devprof.device_ms_by_category", "ph": "C", "ts": ts_us,
                "pid": 0, "tid": 0,
                "args": {
                    k: round(v * device * 1e3, 4) for k, v in shares.items()
                },
            }
        )
        _CHROME_EVENTS.append(
            {
                "name": "devprof.step_segments_ms", "ph": "C", "ts": ts_us,
                "pid": 0, "tid": 0,
                "args": {
                    "host_prep": round(host_prep * 1e3, 4),
                    "dispatch_gap": round(dispatch * 1e3, 4),
                    "device": round(device * 1e3, 4),
                },
            }
        )
    return entry


def drain_chrome_events() -> List[Dict[str, Any]]:
    """Drain the counter-track buffer (what ``profiler.Profiler.export``
    merges into its traceEvents stream)."""
    import os as _os

    with _CHROME_LOCK:
        out, n = list(_CHROME_EVENTS), len(_CHROME_EVENTS)
        _CHROME_EVENTS.clear()
    pid = _os.getpid()
    tid = threading.get_ident()
    for ev in out:
        ev["pid"], ev["tid"] = pid, tid
    return out[:n]


def summarize_timeline(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate view of a step-timeline ring for /healthz, incident
    snapshots and bench records: mean segment split, mean per-category
    shares, and the measured comm share with its source breakdown."""
    if not entries:
        return {"enabled": _ENABLED[0], "sampled_steps": 0}
    n = len(entries)
    walls = [e.get("wall_s", 0.0) for e in entries]
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731 - tiny local aggregator
    cats = {k: mean([e.get("categories", {}).get(k, 0.0) for e in entries])
            for k in CATEGORIES}
    sources: Dict[str, int] = {}
    for e in entries:
        src = e.get("comm_source", "none")
        sources[src] = sources.get(src, 0) + 1
    return {
        "enabled": _ENABLED[0],
        "sampled_steps": n,
        "mean_wall_ms": round(mean(walls) * 1e3, 4),
        "mean_host_bubble_fraction": round(
            mean([e.get("host_bubble_fraction", 0.0) for e in entries]), 4
        ),
        "mean_device_ms": round(
            mean([e.get("device_s", 0.0) for e in entries]) * 1e3, 4
        ),
        "mean_category_shares": {k: round(v, 4) for k, v in cats.items()},
        "comm_share_measured": round(cats.get("collective", 0.0), 4),
        "comm_sources": sources,
        "last": dict(entries[-1]),
    }
