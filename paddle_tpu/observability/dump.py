"""CLI: ``python -m paddle_tpu.observability.dump [--to-chrome OUT] path``

Postmortem reader for the observability artifacts:

- a **flight-recorder dump** (``flightrec_*.json``, schema
  ``paddle_tpu.flight_recorder/v1``) is pretty-printed as a timeline —
  reason, dump walltime, then one line per event with its offset from the
  newest event;
- a **span JSONL** (``Tracer.export_jsonl`` output) is summarized per
  trace, or converted to a chrome-trace JSON with ``--to-chrome OUT``
  (load it in ``chrome://tracing`` / Perfetto);
- an **incident directory** (``incident_*/``, schema
  ``paddle_tpu.incident/v1`` — written by
  ``observability.aggregate.ClusterObserver``) is rendered as ONE
  cross-replica timeline: every replica's flight ring plus the global ring
  merged by timestamp with a source column, the router's recent routing
  decisions, the SLO state timeline, and the sampled span trees — a
  failed-over request's spans from BOTH replicas assemble into one tree by
  trace_id, each span annotated with the replica that emitted it;
- ``--devprof`` renders the **device-time attribution** story instead: the
  sampled step-timeline (t-rel, host-prep / dispatch-gap / device split,
  host-bubble fraction, comm source, per-category device shares) from the
  dump's ``devprof_step`` events, plus — for incident dirs — the cost
  ledger and any cost regressions; exits 2 when the dump carries no
  profiles or a profile row is malformed, never a vacuous pass.

Exit status: 0 on success, 2 on a missing, empty or corrupt file or
incident directory (including a manifest referencing a missing ring) — the
same no-vacuous-pass discipline as the analyzer CLI: a typo'd path in a
postmortem script must fail loudly, never print an empty timeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.observability.aggregate import INCIDENT_SCHEMA
from paddle_tpu.observability.flight_recorder import DUMP_SCHEMA
from paddle_tpu.observability.tracing import Tracer


def _load(path: str) -> Any:
    """Classify + parse: a flight dump (one JSON object with our schema), a
    span JSONL (one record per line), else ValueError."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        raise ValueError("file is empty")
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and obj.get("schema") == DUMP_SCHEMA:
            return ("flight", obj)
        if isinstance(obj, dict) and "events" in obj and "reason" in obj:
            return ("flight", obj)
    except ValueError:
        pass  # not a single JSON document — try JSONL below
    records: List[Dict[str, Any]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as exc:
            raise ValueError(f"line {lineno} is not valid JSON: {exc}") from exc
        if not isinstance(rec, dict) or "name" not in rec or "ts_us" not in rec:
            raise ValueError(
                f"line {lineno} is not a span record (need 'name' and 'ts_us')"
            )
        records.append(rec)
    if not records:
        raise ValueError("no span records found")
    return ("spans", records)


def _print_flight(dump: Dict[str, Any]) -> None:
    events = dump.get("events", [])
    print(f"flight-recorder dump — reason: {dump.get('reason', '?')}")
    print(
        f"pid {dump.get('pid', '?')}, walltime {dump.get('walltime', '?')}, "
        f"{len(events)} events"
    )
    extra = dump.get("extra") or {}
    if extra:
        print(f"extra: {json.dumps(extra, default=str)}")
    if not events:
        print("(empty ring)")
        return
    newest = max(float(e.get("ts_us", 0.0)) for e in events)
    print(f"{'t-rel':>10}  {'kind':<24} fields")
    for e in events:
        rel = (float(e.get("ts_us", 0.0)) - newest) / 1e6
        fields = {
            k: v
            for k, v in e.items()
            if k not in ("seq", "ts_us", "walltime", "kind")
        }
        print(
            f"{rel:>+9.3f}s  {str(e.get('kind', '?')):<24} "
            f"{json.dumps(fields, default=str)}"
        )


def _print_spans(records: List[Dict[str, Any]]) -> None:
    spans = [r for r in records if r.get("kind", "span") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        traces.setdefault(str(s.get("trace_id")), []).append(s)
    print(f"{len(spans)} spans, {len(events)} events, {len(traces)} traces")
    for tid, group in traces.items():
        _print_trace_tree(tid, group)


def _span_replicas(group: List[Dict[str, Any]]) -> List[str]:
    """Every replica named by a trace's spans (the ``replica`` attr the
    scoped frontends stamp, plus the router.failover bridge's endpoints)."""
    out: List[str] = []
    for s in group:
        attrs = s.get("attrs") or {}
        for key in ("replica", "from_replica", "to_replica"):
            v = attrs.get(key)
            if v is not None and str(v) not in out:
                out.append(str(v))
    return out


def _print_trace_tree(tid: str, group: List[Dict[str, Any]]) -> None:
    group.sort(key=lambda s: s["ts_us"])
    replicas = _span_replicas(group)
    tag = f"  [replicas: {', '.join(replicas)}]" if len(replicas) > 1 else ""
    print(f"trace {tid}:{tag}")
    by_id = {s.get("span_id"): s for s in group}
    for s in group:
        depth = 0
        cur = s
        seen = set()  # a corrupt cyclic parent chain must not hang us
        while (
            cur is not None
            and cur.get("parent_id") in by_id
            and id(cur) not in seen
        ):
            seen.add(id(cur))
            depth += 1
            cur = by_id[cur["parent_id"]]
        dur_ms = float(s.get("dur_us", 0.0)) / 1e3
        attrs = s.get("attrs") or {}
        note = ""
        if attrs.get("replica") is not None:
            note = f"  @{attrs['replica']}"
        elif attrs.get("from_replica") is not None:
            note = f"  @{attrs['from_replica']}->{attrs.get('to_replica')}"
        print(
            f"  {'  ' * depth}{s['name']}  {dur_ms:.3f} ms"
            f"  [{s.get('status', 'ok')}]{note}"
        )


def _to_chrome(records: List[Dict[str, Any]], out: str) -> int:
    events = []
    for rec in records:
        rec = dict(rec)
        rec.setdefault("kind", "span")
        rec.setdefault("dur_us", 0.0)
        rec.setdefault("attrs", {})
        events.append(Tracer._to_chrome(rec))
    with open(out, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


class _CorruptIncident(ValueError):
    pass


def _load_incident(dirpath: str) -> Dict[str, Any]:
    """Validate + load an incident directory; raises ``_CorruptIncident``
    on anything short of a complete, schema-correct incident — a partial
    dir must fail the postmortem script, never render a partial story."""
    manifest_path = os.path.join(dirpath, "incident.json")
    if not os.path.isfile(manifest_path):
        raise _CorruptIncident("no incident.json manifest (torn or not an incident dir)")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except ValueError as exc:
        raise _CorruptIncident(f"incident.json is not valid JSON: {exc}") from exc
    if not isinstance(manifest, dict) or manifest.get("schema") != INCIDENT_SCHEMA:
        raise _CorruptIncident(
            f"manifest schema is {manifest.get('schema')!r}, expected {INCIDENT_SCHEMA!r}"
        )
    files = manifest.get("files") or {}
    rings: Dict[str, Dict[str, Any]] = {}
    for fname in files.get("flight", []):
        ring_path = os.path.join(dirpath, fname)
        if not os.path.isfile(ring_path):
            raise _CorruptIncident(f"manifest references missing ring file {fname}")
        try:
            with open(ring_path) as f:
                ring = json.load(f)
        except ValueError as exc:
            raise _CorruptIncident(f"{fname} is not valid JSON: {exc}") from exc
        if not isinstance(ring, dict) or ring.get("schema") != DUMP_SCHEMA:
            raise _CorruptIncident(f"{fname} is not a flight dump")
        rings[fname] = ring
    spans: List[Dict[str, Any]] = []
    span_file = files.get("spans")
    if span_file:
        span_path = os.path.join(dirpath, span_file)
        if not os.path.isfile(span_path):
            raise _CorruptIncident(f"manifest references missing span file {span_file}")
        with open(span_path) as f:
            for lineno, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    spans.append(json.loads(line))
                except ValueError as exc:
                    raise _CorruptIncident(
                        f"{span_file} line {lineno} is not valid JSON: {exc}"
                    ) from exc
    routing: Optional[Dict[str, Any]] = None
    routing_file = files.get("routing")
    if routing_file:
        routing_path = os.path.join(dirpath, routing_file)
        if not os.path.isfile(routing_path):
            # same fail-loud contract as the rings: a manifest-referenced
            # artifact that is gone means a torn copy, not an empty section
            raise _CorruptIncident(
                f"manifest references missing routing file {routing_file}"
            )
        try:
            with open(routing_path) as f:
                routing = json.load(f)
        except ValueError as exc:
            raise _CorruptIncident(f"{routing_file} is not valid JSON: {exc}") from exc
    devprof: Optional[Dict[str, Any]] = None
    devprof_file = files.get("devprof")
    if devprof_file:
        devprof_path = os.path.join(dirpath, devprof_file)
        if not os.path.isfile(devprof_path):
            raise _CorruptIncident(
                f"manifest references missing devprof file {devprof_file}"
            )
        try:
            with open(devprof_path) as f:
                devprof = json.load(f)
        except ValueError as exc:
            raise _CorruptIncident(f"{devprof_file} is not valid JSON: {exc}") from exc
        if not isinstance(devprof, dict):
            raise _CorruptIncident(f"{devprof_file} is not a devprof section")
    return {
        "manifest": manifest, "rings": rings, "spans": spans,
        "routing": routing, "devprof": devprof,
    }


def _ring_source(fname: str, ring: Dict[str, Any]) -> str:
    scope = ring.get("scope") or {}
    if scope.get("replica"):
        return str(scope["replica"])
    if fname == "flight_global.json":
        return "global"
    return fname.replace("flight_", "").replace(".json", "")


def _print_incident(incident: Dict[str, Any]) -> None:
    manifest = incident["manifest"]
    print(f"incident — reason: {manifest.get('reason', '?')}")
    print(
        f"pid {manifest.get('pid', '?')}, walltime {manifest.get('walltime', '?')}, "
        f"replicas: {', '.join(manifest.get('replicas', []))}"
    )
    healthz = manifest.get("healthz") or {}
    replicas = healthz.get("replicas") or {}
    if replicas:
        states = ", ".join(f"{n}={e.get('state')}" for n, e in sorted(replicas.items()))
        print(f"replica states: {states}")
    slo = healthz.get("slo") or {}
    if slo:
        print(f"slo state: {slo.get('state')}  burn: {json.dumps(slo.get('burn', {}))}")
        for e in slo.get("timeline", []):
            print(
                f"  slo {e.get('from')} -> {e.get('to')} "
                f"(signal={e.get('signal')}, burn={e.get('burn')})"
            )
    # ONE cross-replica timeline: every ring's events, tagged + merged.
    # The global ring holds the tagged tee of every replica event plus the
    # untagged router/process events — dedup by identity (seq, ts, source)
    merged: List[Dict[str, Any]] = []
    seen = set()
    for fname, ring in sorted(incident["rings"].items()):
        source = _ring_source(fname, ring)
        for e in ring.get("events", []):
            src = str(e.get("replica", source if source != "global" else "process"))
            key = (e.get("seq"), e.get("ts_us"), src, e.get("kind"))
            if key in seen:
                continue
            seen.add(key)
            merged.append({**e, "_source": src})
    merged.sort(key=lambda e: float(e.get("ts_us", 0.0)))
    print(f"\ncross-replica timeline ({len(merged)} events):")
    if merged:
        newest = max(float(e.get("ts_us", 0.0)) for e in merged)
        print(f"{'t-rel':>10}  {'source':<10} {'kind':<24} fields")
        for e in merged:
            rel = (float(e.get("ts_us", 0.0)) - newest) / 1e6
            fields = {
                k: v for k, v in e.items()
                if k not in ("seq", "ts_us", "walltime", "kind", "_source", "replica")
            }
            print(
                f"{rel:>+9.3f}s  {e['_source']:<10} "
                f"{str(e.get('kind', '?')):<24} {json.dumps(fields, default=str)}"
            )
    routing = incident.get("routing")
    if routing:
        log = routing.get("log", [])
        print(
            f"\nrouting: {routing.get('dispatches', 0)} dispatches, "
            f"counters {json.dumps(routing.get('counters', {}))}, "
            f"sheds {json.dumps(routing.get('sheds', {}))}, "
            f"salvaged {routing.get('salvaged', 0)}"
        )
        for entry in log[-20:]:
            print(f"  {json.dumps(entry, default=str)}")
    spans = [r for r in incident["spans"] if r.get("kind", "span") == "span"]
    if spans:
        traces: Dict[str, List[Dict[str, Any]]] = {}
        for s in spans:
            traces.setdefault(str(s.get("trace_id")), []).append(s)
        # cross-replica traces first: the failover story is the headline
        def cross(tid: str) -> int:
            return -len(_span_replicas(traces[tid]))

        print(f"\nspan trees ({len(spans)} spans, {len(traces)} traces):")
        for tid in sorted(traces, key=cross):
            _print_trace_tree(tid, traces[tid])


def _devprof_steps(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Pull the sampled step profiles out of a flight event stream,
    validating their shape — a malformed profile raises (exit 2 upstream),
    it never renders as a half-empty row."""
    steps = []
    for e in events:
        if e.get("kind") != "devprof_step":
            continue
        cats = e.get("categories")
        if not isinstance(cats, dict) or "wall_ms" not in e:
            raise ValueError(
                f"corrupt devprof_step event (seq={e.get('seq')}): "
                "missing categories/wall_ms"
            )
        steps.append(e)
    return steps


def _print_devprof(
    steps: List[Dict[str, Any]], cost: Optional[Dict[str, Any]] = None
) -> None:
    """Render the step-timeline: one row per sampled step (t-rel, segment
    split, comm source, per-category shares), then the top-category summary
    and — when an incident carried one — the cost ledger + regressions."""
    print(f"device-time attribution — {len(steps)} sampled steps")
    newest = max(float(e.get("ts_us", 0.0)) for e in steps)
    print(
        f"{'t-rel':>10} {'step':>6} {'wall ms':>9} {'host ms':>9} "
        f"{'disp ms':>9} {'dev ms':>9} {'bubble':>7} {'comm':<10} shares"
    )
    totals: Dict[str, float] = {}
    for e in steps:
        rel = (float(e.get("ts_us", 0.0)) - newest) / 1e6
        cats = e["categories"]
        for k, v in cats.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        shares = " ".join(f"{k}={float(v):.2f}" for k, v in sorted(cats.items()))
        print(
            f"{rel:>+9.3f}s {e.get('step', '?'):>6} "
            f"{float(e.get('wall_ms', 0.0)):>9.3f} "
            f"{float(e.get('host_prep_ms', 0.0)):>9.3f} "
            f"{float(e.get('dispatch_ms', 0.0)):>9.3f} "
            f"{float(e.get('device_ms', 0.0)):>9.3f} "
            f"{float(e.get('host_bubble_fraction', 0.0)):>7.3f} "
            f"{str(e.get('comm_source', 'none')):<10} {shares}"
        )
    n = len(steps)
    means = sorted(
        ((k, v / n) for k, v in totals.items()), key=lambda kv: -kv[1]
    )
    top = means[0] if means else ("?", 0.0)
    print(f"\ntop category: {top[0]} (mean device share {top[1]:.3f})")
    print(
        "mean shares: "
        + "  ".join(f"{k}={v:.3f}" for k, v in means)
    )
    bubble = sum(float(e.get("host_bubble_fraction", 0.0)) for e in steps) / n
    print(f"mean host-bubble fraction: {bubble:.3f}")
    if cost:
        ledger = cost.get("cost_ledger") or {}
        profiles = ledger.get("profiles") or {}
        for fn, by_sig in sorted(profiles.items()):
            for sig, prof in sorted(by_sig.items()):
                print(
                    f"cost profile: {fn} [{sig}] flops={prof.get('flops')} "
                    f"bytes={prof.get('bytes_accessed')} "
                    f"model={prof.get('cost_model')}"
                )
        for r in ledger.get("regressions") or []:
            print(
                f"COST REGRESSION: {r.get('fn')} {r.get('prev_signature')} -> "
                f"{r.get('signature')} drift_flops={r.get('drift_flops')} "
                f"drift_bytes={r.get('drift_bytes')}"
            )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.dump",
        description="Pretty-print a flight-recorder dump, or summarize / "
        "convert a tracer span JSONL.",
    )
    ap.add_argument(
        "path",
        help="flight-recorder dump (.json), span JSONL, or incident directory",
    )
    ap.add_argument(
        "--to-chrome",
        metavar="OUT",
        help="convert a span JSONL to a chrome-trace JSON file",
    )
    ap.add_argument(
        "--devprof",
        action="store_true",
        help="render the device-time attribution story: the sampled "
        "step-timeline (segment split + per-category shares) from a flight "
        "dump's devprof_step events, plus the cost ledger/regressions when "
        "reading an incident dir; exits 2 when the dump carries no profiles",
    )
    args = ap.parse_args(argv)

    if os.path.isdir(args.path):
        try:
            incident = _load_incident(args.path)
        except (_CorruptIncident, OSError) as exc:
            print(
                f"error: cannot read incident dir {args.path}: {exc}",
                file=sys.stderr,
            )
            return 2
        if args.devprof:
            try:
                steps: List[Dict[str, Any]] = []
                for _fname, ring in sorted(incident["rings"].items()):
                    steps.extend(_devprof_steps(ring.get("events", [])))
            except ValueError as exc:
                print(f"error: {exc}", file=sys.stderr)
                return 2
            if not steps:
                print(
                    f"error: incident {args.path} carries no devprof_step "
                    "profiles (was FLAGS_devprof_sample_rate 0?)",
                    file=sys.stderr,
                )
                return 2
            steps.sort(key=lambda e: float(e.get("ts_us", 0.0)))
            _print_devprof(steps, cost=incident.get("devprof"))
            return 0
        if args.to_chrome:
            # convert the incident's sampled span buffer (an explicitly
            # requested conversion must never be silently dropped)
            if not incident["spans"]:
                print(
                    f"error: incident {args.path} carries no span buffer "
                    "to convert",
                    file=sys.stderr,
                )
                return 2
            n = _to_chrome(incident["spans"], args.to_chrome)
            print(f"wrote {n} traceEvents to {args.to_chrome}")
            return 0
        _print_incident(incident)
        return 0
    if not os.path.isfile(args.path):
        print(f"error: no such file: {args.path}", file=sys.stderr)
        return 2
    try:
        which, payload = _load(args.path)
    except (ValueError, OSError) as exc:
        print(f"error: cannot read {args.path}: {exc}", file=sys.stderr)
        return 2

    if args.devprof:
        if which != "flight":
            print(
                "error: --devprof reads a flight dump or incident dir "
                "(span JSONLs carry no devprof_step events)",
                file=sys.stderr,
            )
            return 2
        try:
            steps = _devprof_steps(payload.get("events", []))
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        if not steps:
            print(
                f"error: {args.path} carries no devprof_step profiles "
                "(was FLAGS_devprof_sample_rate 0?)",
                file=sys.stderr,
            )
            return 2
        _print_devprof(steps)
        return 0

    if args.to_chrome:
        if which == "flight":
            # a flight dump converts too: events become instant marks
            records = [
                {"kind": "event", "name": e.get("kind", "?"),
                 "ts_us": e.get("ts_us", 0.0),
                 "attrs": {k: v for k, v in e.items()
                           if k not in ("kind", "ts_us")}}
                for e in payload.get("events", [])
            ]
        else:
            records = payload
        n = _to_chrome(records, args.to_chrome)
        print(f"wrote {n} traceEvents to {args.to_chrome}")
        return 0

    if which == "flight":
        _print_flight(payload)
    else:
        _print_spans(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
