"""CLI: ``python -m paddle_tpu.observability.dump [--to-chrome OUT] file``

Postmortem reader for the observability artifacts:

- a **flight-recorder dump** (``flightrec_*.json``, schema
  ``paddle_tpu.flight_recorder/v1``) is pretty-printed as a timeline —
  reason, dump walltime, then one line per event with its offset from the
  newest event;
- a **span JSONL** (``Tracer.export_jsonl`` output) is summarized per
  trace, or converted to a chrome-trace JSON with ``--to-chrome OUT``
  (load it in ``chrome://tracing`` / Perfetto).

Exit status: 0 on success, 2 on a missing, empty or corrupt file — the
same no-vacuous-pass discipline as the analyzer CLI: a typo'd path in a
postmortem script must fail loudly, never print an empty timeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.observability.flight_recorder import DUMP_SCHEMA
from paddle_tpu.observability.tracing import Tracer


def _load(path: str) -> Any:
    """Classify + parse: a flight dump (one JSON object with our schema), a
    span JSONL (one record per line), else ValueError."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        raise ValueError("file is empty")
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and obj.get("schema") == DUMP_SCHEMA:
            return ("flight", obj)
        if isinstance(obj, dict) and "events" in obj and "reason" in obj:
            return ("flight", obj)
    except ValueError:
        pass  # not a single JSON document — try JSONL below
    records: List[Dict[str, Any]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as exc:
            raise ValueError(f"line {lineno} is not valid JSON: {exc}") from exc
        if not isinstance(rec, dict) or "name" not in rec or "ts_us" not in rec:
            raise ValueError(
                f"line {lineno} is not a span record (need 'name' and 'ts_us')"
            )
        records.append(rec)
    if not records:
        raise ValueError("no span records found")
    return ("spans", records)


def _print_flight(dump: Dict[str, Any]) -> None:
    events = dump.get("events", [])
    print(f"flight-recorder dump — reason: {dump.get('reason', '?')}")
    print(
        f"pid {dump.get('pid', '?')}, walltime {dump.get('walltime', '?')}, "
        f"{len(events)} events"
    )
    extra = dump.get("extra") or {}
    if extra:
        print(f"extra: {json.dumps(extra, default=str)}")
    if not events:
        print("(empty ring)")
        return
    newest = max(float(e.get("ts_us", 0.0)) for e in events)
    print(f"{'t-rel':>10}  {'kind':<24} fields")
    for e in events:
        rel = (float(e.get("ts_us", 0.0)) - newest) / 1e6
        fields = {
            k: v
            for k, v in e.items()
            if k not in ("seq", "ts_us", "walltime", "kind")
        }
        print(
            f"{rel:>+9.3f}s  {str(e.get('kind', '?')):<24} "
            f"{json.dumps(fields, default=str)}"
        )


def _print_spans(records: List[Dict[str, Any]]) -> None:
    spans = [r for r in records if r.get("kind", "span") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        traces.setdefault(str(s.get("trace_id")), []).append(s)
    print(f"{len(spans)} spans, {len(events)} events, {len(traces)} traces")
    for tid, group in traces.items():
        group.sort(key=lambda s: s["ts_us"])
        print(f"trace {tid}:")
        by_id = {s.get("span_id"): s for s in group}
        for s in group:
            depth = 0
            cur = s
            seen = set()  # a corrupt cyclic parent chain must not hang us
            while (
                cur is not None
                and cur.get("parent_id") in by_id
                and id(cur) not in seen
            ):
                seen.add(id(cur))
                depth += 1
                cur = by_id[cur["parent_id"]]
            dur_ms = float(s.get("dur_us", 0.0)) / 1e3
            print(
                f"  {'  ' * depth}{s['name']}  {dur_ms:.3f} ms"
                f"  [{s.get('status', 'ok')}]"
            )


def _to_chrome(records: List[Dict[str, Any]], out: str) -> int:
    events = []
    for rec in records:
        rec = dict(rec)
        rec.setdefault("kind", "span")
        rec.setdefault("dur_us", 0.0)
        rec.setdefault("attrs", {})
        events.append(Tracer._to_chrome(rec))
    with open(out, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.dump",
        description="Pretty-print a flight-recorder dump, or summarize / "
        "convert a tracer span JSONL.",
    )
    ap.add_argument("path", help="flight-recorder dump (.json) or span JSONL")
    ap.add_argument(
        "--to-chrome",
        metavar="OUT",
        help="convert a span JSONL to a chrome-trace JSON file",
    )
    args = ap.parse_args(argv)

    if not os.path.isfile(args.path):
        print(f"error: no such file: {args.path}", file=sys.stderr)
        return 2
    try:
        which, payload = _load(args.path)
    except (ValueError, OSError) as exc:
        print(f"error: cannot read {args.path}: {exc}", file=sys.stderr)
        return 2

    if args.to_chrome:
        if which == "flight":
            # a flight dump converts too: events become instant marks
            records = [
                {"kind": "event", "name": e.get("kind", "?"),
                 "ts_us": e.get("ts_us", 0.0),
                 "attrs": {k: v for k, v in e.items()
                           if k not in ("kind", "ts_us")}}
                for e in payload.get("events", [])
            ]
        else:
            records = payload
        n = _to_chrome(records, args.to_chrome)
        print(f"wrote {n} traceEvents to {args.to_chrome}")
        return 0

    if which == "flight":
        _print_flight(payload)
    else:
        _print_spans(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
