"""Recompile watchdog: compile-count honesty for every traced entry point.

A TPU serving number is only trustworthy when retraces are measured, not
assumed (the Ragged Paged Attention point, PAPERS.md): one surprise retrace
mid-serve costs seconds and silently turns a latency benchmark into a compile
benchmark. The watchdog counts every compilation with cause attribution:

- ``first_call``      — the function's first trace (expected, free of blame);
- ``new_shape_dtype`` — a new input shape/dtype bucket forced a retrace;
- ``mode_flip``       — train()/eval() flipped on a reachable Layer, baking a
                        different dropout/batch-norm program.

Feeders: ``jit/api.py`` (StaticFunction cache misses, with cause derived
from the cache key) and the serving engine's two jitted entry points.
Counting is ALWAYS on — a compile costs seconds, so recording one is never
overhead and retrace warnings must fire in production even with metrics off —
but the ``jit_compiles_total`` metric it feeds respects
``FLAGS_enable_metrics`` like every other recording.

``FLAGS_max_compiles_per_fn`` budgets RE-compiles: only compiles past a
function's ``first_call`` traces count against it (N engine instances sharing
a fn name can't trip it); when exceeded, a ``RecompileBudgetWarning`` fires
with the cause breakdown (0 disables).
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Callable, Dict, Optional

from paddle_tpu.flags import GLOBAL_FLAGS

from . import devprof as _devprof
from . import flight_recorder as _flight
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = [
    "CAUSE_FIRST_CALL",
    "CAUSE_NEW_SHAPE_DTYPE",
    "CAUSE_MODE_FLIP",
    "RecompileBudgetWarning",
    "RecompileWatchdog",
    "GLOBAL_WATCHDOG",
    "get_watchdog",
]

CAUSE_FIRST_CALL = "first_call"
CAUSE_NEW_SHAPE_DTYPE = "new_shape_dtype"
CAUSE_MODE_FLIP = "mode_flip"

_MAX_SIGNATURES = 32  # per-fn cap so a retrace storm can't grow host memory

# FLAGS_max_compiles_per_fn as an on_change-cached local: record_compile is
# reachable from the engine's step loops, so even its once-per-compile flag
# read follows the no-registry-lock-on-hot-paths discipline (CC704)
_BUDGET = [0]


def _refresh_budget(value: Any) -> None:
    _BUDGET[0] = int(value or 0)


GLOBAL_FLAGS.on_change("max_compiles_per_fn", _refresh_budget)
_BUDGET[0] = int(GLOBAL_FLAGS.get("max_compiles_per_fn") or 0)  # seeds env


class RecompileBudgetWarning(UserWarning):
    """One traced function blew through ``FLAGS_max_compiles_per_fn``."""


class RecompileWatchdog:
    """Thread-safe per-function compile ledger with cause attribution."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None) -> None:
        self._lock = threading.Lock()
        self._fns: Dict[str, Dict[str, Any]] = {}
        reg = registry or _metrics.GLOBAL_METRICS
        self._counter = reg.counter(
            "jit_compiles_total",
            "Compilations recorded by the recompile watchdog.",
            labelnames=("fn", "cause"),
        )

    def record_compile(
        self,
        fn: str,
        signature: Any = None,
        cause: str = CAUSE_NEW_SHAPE_DTYPE,
        cost_thunk: Optional[Callable[[], Any]] = None,
        cost_hints: Optional[Dict[str, float]] = None,
    ) -> int:
        """Record one compilation of ``fn``; returns its total compile count.
        Called once per actual trace (cache miss), never per call.

        ``cost_thunk``, when the call site can supply one, is a zero-arg
        callable returning ``compiled.cost_analysis()`` raw output for the
        program just compiled; devprof captures it into the cost-regression
        ledger keyed by this same ``fn``/``signature``. It only runs while
        ``FLAGS_devprof_sample_rate > 0`` (an introspective AOT lowering
        costs a second compile) and never raises. ``cost_hints`` are the
        site's analytic per-category weights (attention/matmul/collective)
        seeding the attribution prior."""
        with self._lock:
            rec = self._fns.setdefault(
                fn, {"count": 0, "causes": {}, "signatures": []}
            )
            rec["count"] += 1
            rec["causes"][cause] = rec["causes"].get(cause, 0) + 1
            if signature is not None and len(rec["signatures"]) < _MAX_SIGNATURES:
                sig = signature if isinstance(signature, str) else repr(signature)
                sig = sig[:200]
                if sig not in rec["signatures"]:
                    rec["signatures"].append(sig)
            count = rec["count"]
            causes = dict(rec["causes"])
        self._counter.labels(fn=fn, cause=cause).inc()
        # a compile costs seconds: always worth a flight-recorder line (the
        # black box's postmortem shows compiles near the failure), and a
        # trace instant when tracing is on (a compile mid-serve explains a
        # latency cliff no span arithmetic can)
        _flight.record_event("compile", fn=fn, cause=cause, count=count)
        if cost_thunk is not None and _devprof.devprof_enabled():
            sig = signature if isinstance(signature, str) else repr(signature)
            _devprof.capture_cost_profile(fn, sig, cost_thunk, cost_hints)
        if _tracing.tracing_enabled():
            _tracing.GLOBAL_TRACER.add_event(
                "jit.compile", attrs={"fn": fn, "cause": cause, "count": count}
            )
        budget = _BUDGET[0]
        # budget counts RE-compiles: first_call traces are expected once per
        # instance (several engines / Layer instances legitimately share one
        # fn name here), so they can never trip the retrace warning
        recompiles = count - causes.get(CAUSE_FIRST_CALL, 0)
        if budget and recompiles > budget:
            warnings.warn(
                f"recompile watchdog: '{fn}' recompiled {recompiles} times "
                f"past its first trace ({count} compiles total, "
                f"FLAGS_max_compiles_per_fn={budget}); causes: {causes} — "
                f"check for unbucketed input shapes or train/eval flips",
                RecompileBudgetWarning,
                stacklevel=3,
            )
        return count

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {fn: rec["count"] for fn, rec in self._fns.items()}

    def total(self) -> int:
        with self._lock:
            return sum(rec["count"] for rec in self._fns.values())

    def report(self) -> Dict[str, Dict[str, Any]]:
        """Deep-copied ledger: {fn: {count, causes, signatures}}."""
        with self._lock:
            return {
                fn: {
                    "count": rec["count"],
                    "causes": dict(rec["causes"]),
                    "signatures": list(rec["signatures"]),
                }
                for fn, rec in self._fns.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._fns.clear()


GLOBAL_WATCHDOG = RecompileWatchdog()


def get_watchdog() -> RecompileWatchdog:
    return GLOBAL_WATCHDOG
