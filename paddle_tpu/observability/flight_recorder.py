"""Always-on flight recorder: the serving stack's black box.

When a run dies permanently — engine recovery exhausted, a collective hung
past the ``CommWatchdog`` timeout, the serving pump thread crashed — the
aggregate metrics say only that it died. This ring buffer records what the
engine was *doing* in the seconds before: admits, evicts/finishes,
recoveries, compiles, fault injections, overload-level transitions. On any
of the three permanent-failure seams the buffer is dumped to a JSON file
automatically, so every postmortem starts with a timeline instead of a
shrug (the reference fork's ``CommTaskManager`` dump-on-timeout discipline,
generalized to the whole serving stack).

Design constraints, in order:

- **always on** — unlike metrics/tracing there is no flag gate: a black box
  that must be enabled before the crash is not a black box. Recording is
  therefore lock-free cheap: one ``deque.append`` (atomic under the GIL) of
  a small dict; the ring (``FLAGS_flight_recorder_size``) bounds memory
  forever;
- **redacted** — dumps must be shippable to a bug report: prompt content
  never enters an event, and :func:`_redact` scrubs denylisted keys
  (``prompt``/``tokens``/...) from events AND caller-supplied extras as a
  second line of defense, replacing values with a length-only marker;
- **dump must never kill the dumper** — :meth:`FlightRecorder.safe_dump`
  swallows everything (including the ``tracing.export`` fault site it
  declares, so CI proves the property); the engine step path and the pump
  thread only ever call the safe form. Dump files are written
  tmp+``os.replace`` so a crash mid-dump leaves no torn file.

Read a dump with ``python -m paddle_tpu.observability.dump <file>``.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from paddle_tpu.flags import GLOBAL_FLAGS

__all__ = [
    "DUMP_SCHEMA",
    "FlightRecorder",
    "GLOBAL_FLIGHT_RECORDER",
    "get_flight_recorder",
    "record_event",
    "safe_dump",
]

DUMP_SCHEMA = "paddle_tpu.flight_recorder/v1"

# keys whose values may carry user content: scrubbed from every dumped event
# (events are written to never include these; the dump redacts regardless)
_REDACT_KEYS = frozenset(
    {"prompt", "prompt_ids", "tokens", "generated", "token_ids", "text", "ids"}
)


def _redact(obj: Any) -> Any:
    """Deep-copy ``obj`` with denylisted keys replaced by a length-only
    marker — a dump can prove HOW MUCH was there without leaking WHAT."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if isinstance(k, str) and k.lower() in _REDACT_KEYS:
                try:
                    n = len(v)  # type: ignore[arg-type]
                except TypeError:
                    n = 1
                out[k] = f"<redacted:{n}>"
            else:
                out[k] = _redact(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [_redact(v) for v in obj]
    return obj


class FlightRecorder:
    """Bounded ring of recent structured events + crash-consistent dumps.

    A recorder can have scoped CHILD rings (:meth:`child`): one ring per
    replica, each event tagged with the scope fields and teed into the
    parent — the process-global black box stays complete (the three
    permanent-failure dump seams still capture everything) while the
    cluster incident writer can dump each replica's own ring as a separate,
    attributable file."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        scope: Optional[Dict[str, str]] = None,
        parent: Optional["FlightRecorder"] = None,
    ) -> None:
        cap = int(
            GLOBAL_FLAGS.get("flight_recorder_size") if capacity is None else capacity
        )
        if cap < 1:
            raise ValueError(f"flight recorder capacity must be >= 1, got {cap}")
        self._events: deque = deque(maxlen=cap)
        self._seq = itertools.count()
        self._dump_seq = itertools.count()
        self._lock = threading.Lock()  # dumps only; record() never takes it
        self._scope = dict(scope) if scope else None
        self._parent = parent

    def child(self, **scope: Any) -> "FlightRecorder":
        """A scoped ring (e.g. ``recorder.child(replica="r0")``). Events
        recorded through the child land in the child's own ring AND —
        tagged with the scope fields — in this recorder. One level deep:
        a child of a child tees only into its immediate parent."""
        if not scope:
            raise ValueError("a child flight recorder needs at least one scope field")
        return FlightRecorder(
            # analysis: disable=CC701 maxlen is an immutable deque attribute — no ring state is read
            capacity=self._events.maxlen,
            scope={k: str(v) for k, v in scope.items()},
            parent=self,
        )

    def record(self, kind: str, **fields: Any) -> None:
        """Record one event. Lock-free (deque.append is atomic), always on —
        this is the per-admit/per-evict cost, so it stays one small dict
        build + one append (two when scoped: the tee into the parent ring).
        Callers must not pass prompt content."""
        if self._scope is not None:
            # explicit fields win: a router event that already names its
            # replica is never clobbered by the ring's own scope tag
            fields = {**self._scope, **fields}
        event = {
            "seq": next(self._seq),
            "ts_us": time.perf_counter() * 1e6,
            "walltime": time.time(),
            "kind": kind,
            **fields,
        }
        # analysis: disable=CC701 lock-free by design: deque.append is atomic and snapshot() copies defensively with bounded retry
        self._events.append(event)
        if self._parent is not None:
            # the same dict object lands in both rings (events are written
            # once and never mutated); the parent keeps its own capacity
            self._parent._events.append(event)

    def snapshot(self) -> List[Dict[str, Any]]:
        # record() is deliberately lock-free, so copy defensively: a
        # concurrent append can invalidate the copy's iterator mid-flight
        # (RuntimeError: deque mutated during iteration), and the dump
        # seams fire exactly while other threads are still recording —
        # losing the postmortem to that race would defeat the black box
        for _ in range(8):
            try:
                return list(self._events)
            except RuntimeError:  # ring churned mid-copy: retry
                continue
        # ring still churning after retries: index-copy what's reachable
        out: List[Dict[str, Any]] = []
        for i in range(len(self._events)):
            try:
                out.append(self._events[i])
            except IndexError:  # shrunk under us (clear()): take what we have
                break
        return out

    def clear(self) -> None:
        # analysis: disable=CC701 lock-free by design (test reset seam): snapshot() tolerates a concurrent clear via its IndexError fallback
        self._events.clear()

    def _default_dir(self) -> str:
        # analysis: disable=CC704 dump-time only: runs at most once per permanent failure, never per op, and must see a just-set test dir
        configured = str(GLOBAL_FLAGS.get("flight_recorder_dir"))
        return configured or os.path.join(
            tempfile.gettempdir(), "paddle_tpu_flightrec"
        )

    def dump(
        self,
        reason: str,
        path: Optional[str] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Write the redacted ring to a JSON file; returns the path. With no
        explicit path: ``FLAGS_flight_recorder_dir`` (or the system temp
        dir) / ``flightrec_<pid>_<n>_<reason>.json``. Atomic via
        tmp+``os.replace``. Declares the ``tracing.export`` fault site —
        failure seams call :meth:`safe_dump` instead."""
        from paddle_tpu.testing.faults import fault_point  # lazy: import cycle

        fault_point("tracing.export")
        with self._lock:
            n = next(self._dump_seq)
            if path is None:
                d = self._default_dir()
                os.makedirs(d, exist_ok=True)
                safe_reason = "".join(
                    c if c.isalnum() or c in "-_" else "_" for c in reason
                )[:64]
                path = os.path.join(
                    d, f"flightrec_{os.getpid()}_{n}_{safe_reason}.json"
                )
            payload = {
                "schema": DUMP_SCHEMA,
                "reason": reason,
                "pid": os.getpid(),
                "walltime": time.time(),
                "scope": dict(self._scope) if self._scope else None,
                "extra": _redact(dict(extra) if extra else {}),
                "events": [_redact(e) for e in self.snapshot()],
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return path

    def safe_dump(
        self,
        reason: str,
        path: Optional[str] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Dump that never raises (None on failure) — the ONLY form the
        engine step path, pump thread and watchdog may call: the black box
        must never take down the path whose death it is documenting."""
        try:
            return self.dump(reason, path=path, extra=extra)
        except Exception:  # dump is best-effort by contract on failure seams
            return None


GLOBAL_FLIGHT_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return GLOBAL_FLIGHT_RECORDER


def record_event(kind: str, **fields: Any) -> None:
    """Record into the process-global recorder (the module-level shorthand
    every instrumented call site uses)."""
    GLOBAL_FLIGHT_RECORDER.record(kind, **fields)


def safe_dump(
    reason: str, path: Optional[str] = None, extra: Optional[Dict[str, Any]] = None
) -> Optional[str]:
    return GLOBAL_FLIGHT_RECORDER.safe_dump(reason, path=path, extra=extra)
