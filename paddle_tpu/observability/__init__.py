"""Runtime telemetry layer (reference: SURVEY §5.1 — exported runtime flags,
profiler, ``DeviceMemoryStat`` accounting).

Three pieces, one substrate every perf/robustness PR reports through:

- a process-global, thread-safe metrics registry (:mod:`.metrics`):
  Counter / Gauge / Histogram with fixed log-scale buckets, near-zero
  overhead while ``FLAGS_enable_metrics`` is off;
- exporters (:mod:`.exporters`): Prometheus text exposition over an opt-in
  localhost HTTP endpoint (``FLAGS_metrics_port``), and a JSONL snapshot
  writer whose snapshots the chrome-trace exporter links into its span
  stream;
- a recompile watchdog (:mod:`.recompile`): compile counts with cause
  attribution (new shape/dtype vs. train/eval flip vs. first call) and a
  ``FLAGS_max_compiles_per_fn`` budget warning;
- a per-request distributed tracer (:mod:`.tracing`): span trees
  (trace/span/parent ids, traceparent propagation) with seeded head
  sampling via ``FLAGS_trace_sample_rate``, zero-cost when off, bounded
  span store, chrome-trace + JSONL export merged by ``profiler.export``;
- a device-time attribution layer (:mod:`.devprof`): compile-time cost
  profiles (``cost_analysis()`` keyed by watchdog signature, with a
  cost-regression ledger), sampled step profiles decomposed into
  host-prep / dispatch-gap / device segments with per-category device
  shares, and a measured per-step collective share — all behind
  ``FLAGS_devprof_sample_rate`` with the same cached-bool off-path;
- an always-on flight recorder (:mod:`.flight_recorder`): lock-cheap ring
  of recent structured events (admits/evicts/recoveries/compiles/faults/
  overload transitions), dumped automatically — redacted — on engine
  permanent failure, watchdog timeout and pump-thread death; read dumps
  with ``python -m paddle_tpu.observability.dump``.

Instrumented call sites: ``inference/engine.py`` (TTFT, decode-step latency,
queue depth, admits/evicts/finished, KV-pool gauges), ``jit/api.py``
(StaticFunction cache misses feed the watchdog), ``distributed/collective.py``
(per-op call/time counters), and the serving front end (:mod:`.serving`
families: shed/deadline/goodput counters, per-priority queue-wait and TTFT
histograms, overload-level gauge).
"""

from paddle_tpu.observability.flight_recorder import (  # noqa: F401
    FlightRecorder,
    GLOBAL_FLIGHT_RECORDER,
    get_flight_recorder,
    record_event,
    safe_dump,
)
from paddle_tpu.observability.tracing import (  # noqa: F401
    GLOBAL_TRACER,
    Span,
    TraceContext,
    Tracer,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    tracing_enabled,
    tracing_full,
)
from paddle_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricScope,
    MetricsRegistry,
    GLOBAL_METRICS,
    get_registry,
    metrics_enabled,
)
from paddle_tpu.observability.slo import (  # noqa: F401
    BurnRateMonitor,
    SLOConfig,
    SLO_STATE_NAMES,
)
from paddle_tpu.observability.aggregate import (  # noqa: F401
    ClusterObserver,
    FLEET_COUNTER_FAMILIES,
    INCIDENT_SCHEMA,
)
from paddle_tpu.observability.devprof import (  # noqa: F401
    CostLedger,
    GLOBAL_COST_LEDGER,
    SampleGate,
    StepTimeline,
    capture_cost_profile,
    devprof_enabled,
    normalize_cost_analysis,
    record_step_profile,
    summarize_timeline,
)
from paddle_tpu.observability.recompile import (  # noqa: F401
    CAUSE_FIRST_CALL,
    CAUSE_MODE_FLIP,
    CAUSE_NEW_SHAPE_DTYPE,
    GLOBAL_WATCHDOG,
    RecompileBudgetWarning,
    RecompileWatchdog,
    get_watchdog,
)
from paddle_tpu.observability.exporters import (  # noqa: F401
    drain_trace_events,
    render_exposition,
    start_metrics_server,
    stop_metrics_server,
    write_snapshot_jsonl,
)
from paddle_tpu.observability.serving import (  # noqa: F401
    PRIORITY_NAMES,
    priority_name,
    serving_metrics,
)

__all__ = [
    "FlightRecorder",
    "GLOBAL_FLIGHT_RECORDER",
    "get_flight_recorder",
    "record_event",
    "safe_dump",
    "GLOBAL_TRACER",
    "Span",
    "TraceContext",
    "Tracer",
    "format_traceparent",
    "get_tracer",
    "parse_traceparent",
    "tracing_enabled",
    "tracing_full",
    "PRIORITY_NAMES",
    "priority_name",
    "serving_metrics",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricScope",
    "MetricsRegistry",
    "GLOBAL_METRICS",
    "get_registry",
    "metrics_enabled",
    "BurnRateMonitor",
    "SLOConfig",
    "SLO_STATE_NAMES",
    "ClusterObserver",
    "FLEET_COUNTER_FAMILIES",
    "INCIDENT_SCHEMA",
    "CostLedger",
    "GLOBAL_COST_LEDGER",
    "SampleGate",
    "StepTimeline",
    "capture_cost_profile",
    "devprof_enabled",
    "normalize_cost_analysis",
    "record_step_profile",
    "summarize_timeline",
    "CAUSE_FIRST_CALL",
    "CAUSE_MODE_FLIP",
    "CAUSE_NEW_SHAPE_DTYPE",
    "GLOBAL_WATCHDOG",
    "RecompileBudgetWarning",
    "RecompileWatchdog",
    "get_watchdog",
    "drain_trace_events",
    "render_exposition",
    "start_metrics_server",
    "stop_metrics_server",
    "write_snapshot_jsonl",
]
