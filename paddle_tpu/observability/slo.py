"""Continuous SLO burn-rate monitor for the serving fleet.

The overload controller (PR 7) reacts to *instantaneous* pressure —
queue depth, pool utilization — and protects the process. Nothing watches
the *service level* continuously: a cluster can sit at a comfortable queue
depth while quietly burning its error budget (sheds trickling, failovers
chewing deadlines, TTFT p99 drifting past target), and the first human
signal is a user complaint. This module is the standard SRE answer,
shaped for the router's probe loop:

- **error budgets**: each signal has a budget (``SLOConfig``) — the
  fraction of requests allowed to miss. The **burn rate** is the observed
  windowed bad fraction divided by the budget: burn 1.0 consumes the
  budget exactly as fast as allowed, burn 4.0 exhausts it 4x faster.
- **multi-window**: each burn rate is evaluated over a FAST and a SLOW
  window and the effective value is ``min(fast, slow)`` — a state
  escalates only when the violation is both *currently happening* (fast)
  and *sustained* (slow), the classic defense against paging on a blip.
- **hysteresis**: OK → WARN → PAGE transitions latch through the PR 7
  :class:`~paddle_tpu.serving.frontend.Hysteresis` gates (distinct
  start/stop thresholds: latched at ``warn_burn``/``page_burn``, released
  at half), so a burn hovering at a threshold cannot flap the state —
  and the PAGE-entry incident snapshot — every probe tick.

Signals, all computed from **cluster truth** (the router's host-side
terminal accounting — valid with metrics off, same discipline as the
overload controller):

- ``slo``: fraction of terminals NOT finishing ok-inside-deadline, over
  budget ``1 - goodput_target``;
- ``shed``: fraction of terminals with any non-ok outcome, over
  ``shed_budget``;
- ``failover``: re-dispatch attempts per routing dispatch, over
  ``failover_budget``;
- ``ttft``: the sampled cluster TTFT p99 over ``ttft_p99_target_s`` (a
  target ratio, not a budget burn — TTFT has no per-request error
  accounting at the router). Its two windows are disjoint so the min is
  meaningful: the "now" half is the max over the fast window, the
  "sustained" half the max over the slow window EXCLUDING the fast one —
  one bad sample can never latch a state by itself.

State transitions emit ``slo_state_transitions_total{to=...}`` + the
``slo_state`` gauge and a ``slo_state`` flight event; the bounded
``timeline`` is what the cluster bench reports as time-in-WARN/PAGE.
Driven by :class:`~paddle_tpu.observability.aggregate.ClusterObserver`
from the router's probe loop; also usable standalone by feeding
:meth:`BurnRateMonitor.observe` cumulative samples.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _metrics

__all__ = [
    "OK",
    "PAGE",
    "SLO_STATE_NAMES",
    "WARN",
    "BurnRateMonitor",
    "SLOConfig",
]

OK, WARN, PAGE = 0, 1, 2
SLO_STATE_NAMES = {OK: "ok", WARN: "warn", PAGE: "page"}

# the monitored signal keys, in reporting order
SIGNALS = ("slo", "shed", "failover", "ttft")


def _flag(name: str) -> Any:
    return GLOBAL_FLAGS.get(name)


@dataclass
class SLOConfig:
    """Targets/budgets/windows; defaults seed from the ``FLAGS_slo_*``
    flags at construction time (never re-read per tick)."""

    ttft_p99_target_s: float = field(
        default_factory=lambda: float(_flag("slo_ttft_p99_target_s"))
    )
    goodput_target: float = field(
        default_factory=lambda: float(_flag("slo_goodput_target"))
    )
    shed_budget: float = field(
        default_factory=lambda: float(_flag("slo_shed_budget"))
    )
    failover_budget: float = field(
        default_factory=lambda: float(_flag("slo_failover_budget"))
    )
    fast_window_s: float = field(
        default_factory=lambda: float(_flag("slo_fast_window_s"))
    )
    slow_window_s: float = field(
        default_factory=lambda: float(_flag("slo_slow_window_s"))
    )
    warn_burn: float = field(default_factory=lambda: float(_flag("slo_warn_burn")))
    page_burn: float = field(default_factory=lambda: float(_flag("slo_page_burn")))
    min_terminals: int = field(
        default_factory=lambda: int(_flag("slo_min_terminals"))
    )
    timeline_size: int = 256

    def __post_init__(self) -> None:
        if not 0.0 < self.goodput_target < 1.0:
            raise ValueError(f"goodput_target must be in (0, 1), got {self.goodput_target}")
        if self.shed_budget <= 0 or self.failover_budget <= 0:
            raise ValueError("shed/failover budgets must be > 0")
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s ({self.fast_window_s}) <= "
                f"slow_window_s ({self.slow_window_s})"
            )
        if self.warn_burn <= 0 or self.page_burn < self.warn_burn:
            raise ValueError(
                f"need 0 < warn_burn ({self.warn_burn}) <= page_burn ({self.page_burn})"
            )
        if self.ttft_p99_target_s <= 0:
            raise ValueError("ttft_p99_target_s must be > 0")
        if self.min_terminals < 1:
            # the trust gate doubles as the division guard: a window must
            # hold at least ONE terminal before its fractions are computed
            raise ValueError(
                f"min_terminals must be >= 1, got {self.min_terminals}"
            )


def _slo_metrics() -> Dict[str, Any]:
    reg = _metrics.GLOBAL_METRICS
    return {
        "state": reg.gauge(
            "slo_state",
            "SLO burn-rate monitor state: 0 ok, 1 warn, 2 page. High-water "
            "mark tracked since reset.",
        ),
        "transitions": reg.counter(
            "slo_state_transitions_total",
            "SLO monitor state transitions, by the state entered "
            "(ok / warn / page).",
            labelnames=("to",),
        ),
        "burn": reg.gauge(
            "slo_burn_rate",
            "Effective (min of fast/slow window) burn rate per signal: "
            "slo (goodput violations), shed, failover, ttft (p99 / target).",
            labelnames=("signal",),
        ),
    }


class BurnRateMonitor:
    """See the module docstring. Feed cumulative samples via
    :meth:`observe`; read :attr:`state` / :attr:`last` / :attr:`timeline`.

    A sample is the dict shape ``ReplicaRouter.slo_sample()`` returns:
    cumulative ``terminals`` / ``ok`` / ``ok_in_slo`` / ``dispatches`` /
    ``redispatches`` plus the instantaneous ``ttft_p99_s``. Not
    thread-safe by itself — the caller (the router probe loop, under the
    router lock) serializes observe()."""

    def __init__(self, config: Optional[SLOConfig] = None) -> None:
        # lazy: the serving layer imports observability at module load;
        # importing it back at module scope here would cycle the packages
        from paddle_tpu.serving.frontend import Hysteresis

        self.config = config or SLOConfig()
        cfg = self.config
        self._warn_gate = Hysteresis(cfg.warn_burn, cfg.warn_burn * 0.5)
        self._page_gate = Hysteresis(cfg.page_burn, cfg.page_burn * 0.5)
        self.state = OK
        self._samples: Deque[Tuple[float, Dict[str, float]]] = deque()
        # (t, from_state, to_state, dominant_signal, burn) transitions; the
        # bench's time-in-WARN/PAGE timeline reads this
        self.timeline: Deque[Dict[str, Any]] = deque(maxlen=int(cfg.timeline_size))
        self.last: Dict[str, Any] = {}  # most recent burn computation
        self._metrics = _slo_metrics()
        self._flight = _flight.GLOBAL_FLIGHT_RECORDER
        self._state_since: Optional[float] = None
        self._last_now: Optional[float] = None
        self._time_in: Dict[int, float] = {OK: 0.0, WARN: 0.0, PAGE: 0.0}

    @property
    def state_name(self) -> str:
        return SLO_STATE_NAMES[self.state]

    # -- sampling -------------------------------------------------------------
    def would_accept(self, now: float) -> bool:
        """Whether :meth:`observe` at ``now`` would ingest (the rate bound
        below). Callers for whom *building* the sample is the expensive
        part — the router tick holds the router lock — check this first."""
        return (
            self._last_now is None
            or now - self._last_now >= self.config.fast_window_s / 64.0
        )

    def observe(self, now: float, sample: Dict[str, float]) -> int:
        """Ingest one cumulative sample at monotonic instant ``now``;
        returns the (possibly new) state.

        Rate-bounded: observe() rides the router pump, which inline drivers
        call in a tight loop — ingesting every tick would retain
        pump_rate x slow_window samples and scan them all per tick under
        the router lock. Samples closer than ``fast_window_s / 64`` to the
        previous one are dropped (one float compare), bounding both the
        deque and the per-tick scan regardless of pump rate."""
        if self._state_since is None:
            self._state_since = now
        if not self.would_accept(now):
            return self.state
        self._last_now = now
        self._samples.append((float(now), dict(sample)))
        horizon = now - self.config.slow_window_s
        # keep ONE sample older than the slow window as the delta baseline
        while len(self._samples) > 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()
        fast, fast_ok = self._window_burns(now, self.config.fast_window_s)
        slow, slow_ok = self._window_burns(now, self.config.slow_window_s)
        # ttft is max-based, so its slow window must EXCLUDE the fast one
        # (a superset max would always equal the fast value and the min
        # would degenerate to single-window alerting)
        t_now, t_sustained = self._ttft_maxes(now)
        fast["ttft"] = t_now / self.config.ttft_p99_target_s
        slow["ttft"] = t_sustained / self.config.ttft_p99_target_s
        effective: Dict[str, float] = {}
        for s in SIGNALS:
            if s == "ttft" or (fast_ok and slow_ok):
                # both windows populated: escalation needs the violation to
                # be both happening now AND sustained
                effective[s] = min(fast[s], slow[s])
            elif slow_ok or fast_ok:
                # an under-populated window must DEFER to the trusted one,
                # never inject 0 into the min — a low-traffic cluster in
                # total failure still has to page off its slow window
                effective[s] = slow[s] if slow_ok else fast[s]
            else:
                effective[s] = 0.0
        dominant = max(SIGNALS, key=lambda s: effective[s])
        overall = effective[dominant]
        self.last = {
            "fast": fast, "slow": slow, "effective": effective,
            "dominant": dominant, "overall": round(overall, 4),
        }
        if _metrics.metrics_enabled():
            for s in SIGNALS:
                self._metrics["burn"].labels(signal=s).set(effective[s])
        warn = self._warn_gate.update(overall)
        page = self._page_gate.update(overall)
        new_state = PAGE if page else WARN if warn else OK
        if new_state != self.state:
            self._transition(new_state, dominant, overall, now)
        return self.state

    def _ttft_maxes(self, now: float) -> Tuple[float, float]:
        """(max sampled p99 over the fast window, max over the slow window
        EXCLUDING the fast window) — the disjoint halves of the ttft
        signal's now/sustained split."""
        fast_start = now - self.config.fast_window_s
        slow_start = now - self.config.slow_window_s
        t_now = t_sustained = 0.0
        for t, s in self._samples:
            v = s.get("ttft_p99_s", 0.0)
            if t >= fast_start:
                t_now = max(t_now, v)
            elif t >= slow_start:
                t_sustained = max(t_sustained, v)
        return t_now, t_sustained

    def _window_burns(
        self, now: float, window_s: float
    ) -> Tuple[Dict[str, float], bool]:
        """Budget burns over ``[now - window_s, now]`` (cumulative deltas
        between the newest sample and the newest sample at-or-before the
        window start, or the oldest retained), plus whether the window held
        enough terminals for its fractions to be trusted. The ttft signal
        is computed separately (:meth:`_ttft_maxes`)."""
        newest = self._samples[-1][1]
        start = now - window_s
        base = self._samples[0][1]
        for t, s in self._samples:
            if t <= start:
                base = s
            else:
                break  # samples are time-ordered: the base is found
        cfg = self.config
        d_term = newest["terminals"] - base["terminals"]
        d_ok = newest["ok"] - base["ok"]
        d_in_slo = newest["ok_in_slo"] - base["ok_in_slo"]
        d_disp = newest["dispatches"] - base["dispatches"]
        d_re = newest["redispatches"] - base["redispatches"]
        out: Dict[str, float] = {}
        if d_term < cfg.min_terminals:
            # too little traffic to trust a fraction: the caller defers to
            # the other window (reading 0 into min() would blind the
            # monitor on exactly the low-traffic outage it must page on)
            out.update({"slo": 0.0, "shed": 0.0, "failover": 0.0})
            return out, False
        out["slo"] = ((d_term - d_in_slo) / d_term) / (1.0 - cfg.goodput_target)
        out["shed"] = ((d_term - d_ok) / d_term) / cfg.shed_budget
        out["failover"] = (d_re / d_disp) / cfg.failover_budget if d_disp else 0.0
        return out, True

    def _transition(self, to: int, signal: str, burn: float, now: float) -> None:
        frm = self.state
        if self._state_since is not None:
            self._time_in[frm] += now - self._state_since
        self._state_since = now
        self.state = to
        self.timeline.append(
            {"t": now, "from": SLO_STATE_NAMES[frm], "to": SLO_STATE_NAMES[to],
             "signal": signal, "burn": round(burn, 4)}
        )
        self._metrics["transitions"].labels(to=SLO_STATE_NAMES[to]).inc()
        self._metrics["state"].set(to)
        self._flight.record(
            "slo_state", **{"from": SLO_STATE_NAMES[frm],
                            "to": SLO_STATE_NAMES[to],
                            "signal": signal, "burn": round(burn, 4)},
        )

    # -- reporting ------------------------------------------------------------
    def time_in_states(self, now: Optional[float] = None) -> Dict[str, float]:
        """Seconds spent in each state so far (current state accrued up to
        ``now``, defaulting to the last observed instant)."""
        out = dict(self._time_in)
        if self._state_since is not None:
            if now is None:
                now = self._last_now if self._last_now is not None else self._state_since
            out[self.state] += max(now, self._state_since) - self._state_since
        return {SLO_STATE_NAMES[k]: round(v, 6) for k, v in out.items()}

    def snapshot(self) -> Dict[str, Any]:
        """The /healthz ``slo`` block."""
        return {
            "state": self.state_name,
            "burn": dict(self.last.get("effective", {})),
            "dominant": self.last.get("dominant"),
            "timeline": [dict(e) for e in list(self.timeline)[-16:]],
            "time_in_states": self.time_in_states(),
        }
