"""Cluster aggregation: the fleet's one observability surface.

PRs 11–14 built the cluster (router, tp shard groups, host KV tier) but
its evidence stayed per-process: metric families mixed every replica into
one unscoped soup, ``/healthz`` knew one frontend, and a failover incident
scattered its story across N flight rings and a span buffer that nothing
correlated. This module joins them:

- :class:`ClusterObserver` rides the router's probe loop (attached via
  ``router.attach_observer``): it feeds the
  :class:`~paddle_tpu.observability.slo.BurnRateMonitor` cluster-truth
  samples every tick, serves the fleet ``/metrics`` (replica-labeled text
  exposition — the scoped cells from ``MetricScope``) and the cluster
  ``/healthz`` (router state + per-replica UP/DEGRADED/DEAD/DRAINING,
  tp_degree, kv-tier, spec acceptance, the SLO block), and reconciles
  fleet sums over the replica-scoped series (:meth:`fleet_counters` —
  every family name it reads is a literal validated by analyzer check
  OB602 and resolved through the strict ``registry.family()``).
- **coordinated incident snapshots**: entering PAGE, any replica death
  (which is how a pump death surfaces at cluster level), and
  all-replicas-dead each dump ONE incident directory under a versioned
  schema (``paddle_tpu.incident/v1``): every replica's own flight ring,
  the process-global ring, the router's recent routing decisions, the
  sampled span buffer, and the cluster health view — rendered as a single
  cross-replica timeline by ``python -m paddle_tpu.observability.dump
  <dir>`` (including a failed-over request's spans from BOTH replicas
  assembled into one tree by trace_id). Writes are best-effort by the
  flight-recorder contract (an incident writer that raises into the probe
  loop would *be* an incident) and rate-limited per reason
  (``FLAGS_incident_cooldown_s``).

Import discipline: this module must not import the serving package at
module scope (``serving`` imports ``observability`` first) — replicas and
the router are duck-typed.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability import devprof as _devprof
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.slo import PAGE, BurnRateMonitor, SLOConfig

__all__ = [
    "FLEET_COUNTER_FAMILIES",
    "INCIDENT_SCHEMA",
    "ClusterObserver",
]

INCIDENT_SCHEMA = "paddle_tpu.incident/v1"

# every fleet-aggregated counter family, by its registered name — read
# back through the strict registry.family() (OB602 validates these
# literals against the package's registered families; a family absent at
# runtime — e.g. kv_tier_* with the tier off — reports as "unregistered"
# rather than silently reading zeros)
FLEET_COUNTER_FAMILIES = (
    "engine_requests_admitted_total",
    "engine_requests_finished_total",
    "engine_slots_evicted_total",
    "engine_recoveries_total",
    "engine_requests_replayed_total",
    "engine_prefill_tokens_computed_total",
    "spec_decode_drafted_tokens_total",
    "spec_decode_accepted_tokens_total",
    "spec_decode_rejected_tokens_total",
    "prefix_cache_hits_total",
    "prefix_cache_misses_total",
    "prefix_cache_evictions_total",
    "kv_tier_spilled_blocks_total",
    "kv_tier_prefetched_blocks_total",
    "kv_tier_dropped_blocks_total",
    "serving_requests_total",
    "serving_shed_total",
    "serving_tokens_total",
    "serving_goodput_tokens_total",
)


class ClusterObserver:
    """See the module docstring. Construct over a
    :class:`~paddle_tpu.serving.router.ReplicaRouter`; attaches itself.

    ``on_tick_locked``/``on_transition_locked`` are called by the router
    UNDER the router lock (lock order router -> frontend -> engine holds
    for everything they touch); the HTTP-facing reads (:meth:`healthz`,
    :meth:`render_metrics`, :meth:`fleet_counters`) take no router lock
    themselves beyond what ``router.snapshot()`` does."""

    def __init__(
        self,
        router: Any,
        slo_config: Optional[SLOConfig] = None,
        incident_dir: Optional[str] = None,
        incident_cooldown_s: Optional[float] = None,
    ) -> None:
        self.router = router
        # replica scoping is anchored to the process-global registry
        # (set_replica_scope resolves scopes there), so the fleet reads
        # must be too — a parallel registry would silently read empty
        self.registry = _metrics.GLOBAL_METRICS
        self.monitor = BurnRateMonitor(slo_config)
        self._incident_dir = incident_dir
        self._cooldown = (
            float(GLOBAL_FLAGS.get("incident_cooldown_s"))
            if incident_cooldown_s is None
            else float(incident_cooldown_s)
        )
        self._incident_seq = itertools.count()
        self._pending_tmp: Optional[str] = None  # staging dir of an in-flight write
        self._last_incident: Dict[str, float] = {}
        self.incidents: List[str] = []  # paths of written incident dirs
        # the TTFT p99 the router samples must age on the monitor's slow
        # window, or a storm's latencies would hold WARN/PAGE on a quiet
        # cluster long after recovery
        router.set_ttft_window(self.monitor.config.slow_window_s)
        router.attach_observer(self)

    # -- probe-loop seams (called under the router lock) ----------------------
    def on_tick_locked(self, now: float) -> None:
        if not self.monitor.would_accept(now):
            return  # don't build the sample the rate bound would drop
        prev = self.monitor.state
        state = self.monitor.observe(now, self.router._slo_sample_locked(now))
        if state == PAGE and prev != PAGE:
            self._maybe_incident_locked("slo_page", now)

    def on_transition_locked(
        self, replica: Any, frm: str, to: str, now: float
    ) -> None:
        if to != "dead":
            return
        # a pump death is observed by the probe as a DEAD transition, so
        # this one seam coordinates both; all-dead gets its own reason
        reason = (
            "all_replicas_dead"
            if not any(r.alive for r in self.router.cluster)
            else f"replica_death_{replica.name}"
        )
        self._maybe_incident_locked(reason, now)

    def _maybe_incident_locked(self, reason: str, now: float) -> None:
        last = self._last_incident.get(reason)
        if last is not None and now - last < self._cooldown:
            return
        path = self.write_incident(reason)
        if path is not None:
            # the cooldown limits successful duplicate postmortems; a FAILED
            # write (full disk, bad dir) must not suppress the next attempt
            # at capturing first evidence — retry frequency is naturally
            # bounded by the triggers (state/replica transitions)
            self._last_incident[reason] = now
            self.incidents.append(path)

    # -- fleet endpoints ------------------------------------------------------
    def render_metrics(self) -> str:
        """The fleet ``/metrics`` body: the whole registry's text
        exposition — replica-scoped cells render with their ``replica=``
        label next to the unscoped ones, so one scrape shows every
        replica's series AND the process-level families. The single-process
        ``start_metrics_server`` serves the SAME exposition (one renderer,
        two ports — the formats agree by construction)."""
        from paddle_tpu.observability.exporters import render_exposition

        return render_exposition(self.registry)

    def healthz(self) -> Dict[str, Any]:
        """The cluster ``/healthz`` payload: router truth, per-replica
        state + capability blocks, the SLO monitor block."""
        replicas: Dict[str, Any] = {}
        for r in self.router.cluster:
            entry: Dict[str, Any] = {
                "state": r.state,
                "generation": r.generation,
                "tp_degree": r.tp_degree,
            }
            try:
                snap = r.frontend.snapshot()
                entry.update(
                    {
                        "level": snap.get("level"),
                        "queue_depth": snap.get("queue_depth"),
                        "live_requests": snap.get("live_requests"),
                        "kv_utilization": snap.get("kv_utilization"),
                        "kv_tier": snap.get("kv_tier"),
                        "spec_decode": snap.get("spec_decode"),
                        "tensor_parallel": snap.get("tensor_parallel"),
                    }
                )
            except Exception as exc:  # noqa: BLE001 - a dead replica's snapshot must not kill the fleet healthz
                entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
            replicas[r.name] = entry
        return {
            "cluster": self.router.snapshot(),
            "replicas": replicas,
            "slo": self.monitor.snapshot(),
        }

    def fleet_counters(self) -> Dict[str, Any]:
        """Fleet roll-up of every :data:`FLEET_COUNTER_FAMILIES` family:
        per-replica scoped totals, their fleet sum, and the unscoped total
        (router-level recordings). The churn property test reconciles these
        against cluster truth after every operation."""
        out: Dict[str, Any] = {}
        for name in FLEET_COUNTER_FAMILIES:
            try:
                fam = self.registry.family(name)
            except KeyError:
                # registered only when its subsystem is on (e.g. kv_tier_*);
                # named explicitly so a typo can never hide as "off"
                out[name] = {"unregistered": True}
                continue
            per_replica = {
                scope[0]: fam.scope_total(scope) for scope in fam.scopes()
            }
            out[name] = {
                "per_replica": per_replica,
                "fleet": sum(per_replica.values()),
                "unscoped": fam.total(),
            }
        return out

    # -- coordinated incident snapshots ---------------------------------------
    def _incident_base(self) -> str:
        if self._incident_dir:
            return self._incident_dir
        configured = str(GLOBAL_FLAGS.get("incident_dir"))
        if configured:
            return configured
        flight_dir = str(GLOBAL_FLAGS.get("flight_recorder_dir"))
        if flight_dir:
            return flight_dir
        return os.path.join(tempfile.gettempdir(), "paddle_tpu_incidents")

    def write_incident(self, reason: str, base_dir: Optional[str] = None) -> Optional[str]:
        """Write ONE correlated incident directory; returns its path, or
        None on any failure — the incident writer runs on the probe loop
        and on death seams, where raising would compound the failure it is
        documenting (the flight recorder's ``safe_dump`` contract).

        Runs synchronously (and, from the probe seams, under the router
        lock): incidents are rare and cooldown-limited, and the evidence is
        captured at the moment of the trigger — the routing stall is one
        bounded multi-file write, a deliberate trade against snapshotting
        state that keeps mutating while an async writer catches up."""
        self._pending_tmp = None
        try:
            return self._write_incident(reason, base_dir)
        except Exception:  # noqa: BLE001 - best-effort by contract on failure seams
            tmp = self._pending_tmp
            if tmp is not None:
                # a failed write is retried on the next trigger (no
                # cooldown); it must not accrete torn .tmp staging dirs
                shutil.rmtree(tmp, ignore_errors=True)
            return None
        finally:
            self._pending_tmp = None

    def _write_incident(self, reason: str, base_dir: Optional[str]) -> str:
        base = base_dir or self._incident_base()
        os.makedirs(base, exist_ok=True)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        )[:64]
        n = next(self._incident_seq)
        final = os.path.join(
            base, f"incident_{os.getpid()}_{n}_{safe_reason}"
        )
        # uniquify against another observer (or PID reuse in a persistent
        # incident dir): a name collision must never drop the evidence
        suffix = 0
        while os.path.exists(final):
            suffix += 1
            final = os.path.join(
                base, f"incident_{os.getpid()}_{n}_{safe_reason}_{suffix}"
            )
        tmp = tempfile.mkdtemp(prefix=os.path.basename(final) + ".tmp.", dir=base)
        self._pending_tmp = tmp  # cleaned up by write_incident on failure
        files: Dict[str, Any] = {"flight": [], "spans": None, "routing": "routing.json"}
        # 1) every replica's own flight ring (dead ones included: their ring
        # is exactly the evidence), each a standard flight dump file
        for r in self.router.cluster:
            rec = getattr(r.frontend, "flight", None)
            if rec is None or rec is _flight.GLOBAL_FLIGHT_RECORDER:
                continue  # unscoped frontend: its events are in the global ring
            fname = f"flight_{r.name}.json"
            rec.dump(
                reason, path=os.path.join(tmp, fname),
                extra={"replica": r.name, "generation": r.generation,
                       "state": r.state},
            )
            files["flight"].append(fname)
        # 2) the process-global ring (router events + anything unscoped)
        _flight.GLOBAL_FLIGHT_RECORDER.dump(
            reason, path=os.path.join(tmp, "flight_global.json"),
            extra={"scope": "global"},
        )
        files["flight"].append("flight_global.json")
        # 3) the router's recent routing decisions + accounting
        routing = {
            "log": self.router.routing_log(),
            "counters": self.router.routing_counters(),
            "dispatches": self.router.dispatch_count(),
            "sheds": self.router.shed_counters(),
            "salvaged": self.router.salvaged_count(),
        }
        with open(os.path.join(tmp, "routing.json"), "w") as f:
            json.dump(routing, f, indent=1, default=str)
        # 4) the sampled span buffer (cross-replica failover trees live here)
        n_spans = _tracing.GLOBAL_TRACER.export_jsonl(
            os.path.join(tmp, "spans.jsonl")
        )
        if n_spans:
            files["spans"] = "spans.jsonl"
        else:
            os.remove(os.path.join(tmp, "spans.jsonl"))
        # 4b) device-time attribution: the cost-regression ledger plus each
        # replica engine's step-timeline summary (per-step devprof_step
        # events already live in the flight rings above; this is the
        # compile-time truth to line them up against)
        devprof = {"cost_ledger": _devprof.GLOBAL_COST_LEDGER.snapshot()}
        timelines: Dict[str, Any] = {}
        for r in self.router.cluster:
            eng = getattr(r.frontend, "engine", None)
            if eng is not None and hasattr(eng, "devprof_stats"):
                timelines[r.name] = eng.devprof_stats()
        devprof["timelines"] = timelines
        with open(os.path.join(tmp, "devprof.json"), "w") as f:
            json.dump(devprof, f, indent=1, default=str)
        files["devprof"] = "devprof.json"
        # 5) the manifest LAST (a dir without incident.json is visibly torn),
        # then the atomic directory commit
        manifest = {
            "schema": INCIDENT_SCHEMA,
            "reason": reason,
            "pid": os.getpid(),
            "walltime": time.time(),
            "replicas": [r.name for r in self.router.cluster],
            "files": files,
            "healthz": self.healthz(),
        }
        with open(os.path.join(tmp, "incident.json"), "w") as f:
            json.dump(manifest, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        for attempt in range(8):  # racing writer grabbed the name: re-uniquify
            try:
                os.rename(tmp, final)
                return final
            except OSError:
                final = f"{final}_{attempt}"
        os.rename(tmp, final)
        return final
