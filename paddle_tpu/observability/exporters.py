"""Metrics exporters: Prometheus text over HTTP, and JSONL snapshots that the
chrome-trace exporter links into its span stream.

- :func:`start_metrics_server` serves ``GET /metrics`` (text exposition
  0.0.4) on localhost. Opt-in: nothing listens unless it is called; with no
  explicit port it reads ``FLAGS_metrics_port`` (0 = disabled).
- :func:`write_snapshot_jsonl` appends one JSON line (walltime + the full
  registry snapshot) to a file AND records a chrome-trace instant event
  carrying the snapshot's path/seq; ``profiler.Profiler.export`` drains those
  events into its ``traceEvents``, so a trace viewer shows exactly when each
  metrics snapshot was taken relative to the recorded spans, and
  ``load_profiler_result`` round-trips the link.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from paddle_tpu.flags import GLOBAL_FLAGS

from . import metrics as _metrics

__all__ = [
    "render_exposition",
    "write_snapshot_jsonl",
    "drain_trace_events",
    "start_metrics_server",
    "stop_metrics_server",
]


def render_exposition(registry: Optional["_metrics.MetricsRegistry"] = None) -> str:
    """THE text-exposition renderer: every ``/metrics`` endpoint — the
    process-level ``start_metrics_server`` and the fleet endpoint on the
    multi-replica serving server — goes through this one function, so the
    formats agree by construction. Replica-scoped cells (``MetricScope``)
    render with their ``replica="..."`` label next to the unscoped
    process-level cells; in a multi-replica process there is no ambiguous
    unscoped mix — each replica's series is attributable."""
    return (registry or _metrics.GLOBAL_METRICS).render_prometheus()

_trace_events: List[Dict[str, Any]] = []
_trace_lock = threading.Lock()
_snapshot_seq = itertools.count()
# a server snapshotting every second with no profiler export draining must
# not grow host memory: keep only the newest link events past this cap
_MAX_TRACE_EVENTS = 4096


def write_snapshot_jsonl(
    path: str, registry: Optional[_metrics.MetricsRegistry] = None
) -> Dict[str, Any]:
    """Append one snapshot line to ``path``; returns the snapshot record.
    ``ts_us`` uses the profiler's clock (perf_counter) so the linked instant
    event lands on the same timeline as RecordEvent spans."""
    reg = registry or _metrics.GLOBAL_METRICS
    seq = next(_snapshot_seq)
    ts_us = time.perf_counter() * 1e6
    record = {
        "seq": seq,
        "ts_us": ts_us,
        "walltime": time.time(),
        "metrics": reg.snapshot(),
    }
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
    with _trace_lock:
        _trace_events.append(
            {
                "name": "metrics_snapshot",
                "ph": "i",
                "s": "p",
                "ts": ts_us,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {"path": path, "seq": seq},
            }
        )
        if len(_trace_events) > _MAX_TRACE_EVENTS:
            del _trace_events[: -_MAX_TRACE_EVENTS]
    return record


def drain_trace_events() -> List[Dict[str, Any]]:
    """Hand the buffered snapshot link events to the chrome-trace exporter."""
    global _trace_events
    with _trace_lock:
        events, _trace_events = _trace_events, []
    return events


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] != "/metrics":
            self.send_error(404, "try /metrics")
            return
        body = render_exposition().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args: Any) -> None:  # silence per-request stderr
        pass


_server: Optional[ThreadingHTTPServer] = None
_server_lock = threading.Lock()


def start_metrics_server(port: Optional[int] = None) -> Optional[ThreadingHTTPServer]:
    """Serve /metrics on 127.0.0.1. ``port=None`` reads ``FLAGS_metrics_port``
    (<= 0 means disabled -> returns None); an explicit ``port=0`` binds an
    ephemeral port (``server.server_address[1]`` has it). Idempotent."""
    global _server
    with _server_lock:
        if _server is not None:
            bound = _server.server_address[1]
            if port not in (None, 0) and int(port) != bound:
                raise RuntimeError(
                    f"metrics server already bound to port {bound}; "
                    f"stop_metrics_server() before requesting port {port}"
                )
            return _server
        if port is None:
            port = int(GLOBAL_FLAGS.get("metrics_port"))
            if port <= 0:
                return None
        srv = ThreadingHTTPServer(("127.0.0.1", int(port)), _MetricsHandler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, daemon=True, name="metrics-http")
        t.start()
        _server = srv
        return srv


def stop_metrics_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
