"""Process-global runtime metrics registry.

TPU-native counterpart of the reference's observability substrate (SURVEY
§5.1: the 179 exported runtime flags, ``DeviceMemoryStat`` peak/current
accounting, host/device profiler): a typed registry of Counters, Gauges and
Histograms that the serving engine, jit layer and collectives report through,
rendered as Prometheus text exposition or JSONL snapshots
(``observability.exporters``).

Gating: every recording call checks a module-local cached copy of
``FLAGS_enable_metrics`` (kept fresh by a flag-change listener), so with
metrics off the hot-path cost is one list indexing — no registry lock, no
dict lookup. Metric *definition* is always allowed; only recording is gated.

Histograms use fixed log-scale buckets (``start * factor**i``), the shape
that keeps decode-latency percentiles meaningful across four orders of
magnitude without per-request allocation.

Scoping (fleet observability): a :class:`MetricScope` is a set of label
pairs — ``registry.scope(replica="r0")`` — resolved ONCE; binding a family
through it (``scope.bind(family)`` / ``scope.bind_all(families)``) returns
a handle with the same recording API whose cells carry the scope labels
appended, so every ``engine_*``/``serving_*`` series a replica records is
attributable per replica while still rolling up into the ONE process-global
family (exposition renders scoped cells with ``replica="..."`` labels next
to the unscoped ones). Per-record cost of a scoped handle is identical to
an unscoped one: the same single cached-bool read on the off path, the same
one family-lock acquisition when recording.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from paddle_tpu.flags import GLOBAL_FLAGS

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricScope",
    "MetricsRegistry",
    "GLOBAL_METRICS",
    "get_registry",
    "metrics_enabled",
]

# cached FLAGS_enable_metrics: plain list read on the hot path; the listener
# keeps it in lockstep with set_flags / env seeding
_ENABLED = [False]


def _refresh_enabled(value: Any) -> None:
    _ENABLED[0] = bool(value)


GLOBAL_FLAGS.on_change("enable_metrics", _refresh_enabled)
_ENABLED[0] = bool(GLOBAL_FLAGS.get("enable_metrics"))  # seeds FLAGS_ env var


def metrics_enabled() -> bool:
    """Current ``FLAGS_enable_metrics`` without touching the flag registry."""
    return _ENABLED[0]


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_labels(names: Sequence[str], key: Sequence[str], extra: str = "") -> str:
    parts = [f'{n}="{_escape(k)}"' for n, k in zip(names, key)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """Base: a named family of cells keyed by label-value tuples.

    Scoped cells (see :class:`MetricScope`) live beside the unscoped ones,
    keyed by the scope's label-value tuple: one family, one lock, one name —
    the scope labels only appear at exposition time."""

    kind = "untyped"

    def __init__(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, ...], Any] = {}
        # scope label NAMES are family-wide (first registration wins, a
        # conflicting second scope raises); cells per scope VALUE tuple
        self._scope_labelnames: Tuple[str, ...] = ()
        self._scoped: Dict[Tuple[str, ...], Dict[Tuple[str, ...], Any]] = {}

    def _label_key(self, kv: Dict[str, Any]) -> Tuple[str, ...]:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric '{self.name}' takes labels {self.labelnames}, got {sorted(kv)}"
            )
        return tuple(str(kv[n]) for n in self.labelnames)

    def _register_scope(self, names: Tuple[str, ...], values: Tuple[str, ...]) -> None:
        with self._lock:
            if self._scope_labelnames and self._scope_labelnames != names:
                raise ValueError(
                    f"metric '{self.name}' already scoped by "
                    f"{self._scope_labelnames}, cannot also scope by {names}"
                )
            if not self._scope_labelnames:
                if set(names) & set(self.labelnames):
                    raise ValueError(
                        f"scope labels {names} collide with metric "
                        f"'{self.name}' labels {self.labelnames}"
                    )
                self._scope_labelnames = names
            self._scoped.setdefault(values, {})

    def _cells_for(self, scope: Optional[Tuple[str, ...]]) -> Dict[Tuple[str, ...], Any]:
        # caller holds self._lock
        if scope is None:
            return self._cells
        cells = self._scoped.get(scope)
        if cells is None:
            cells = self._scoped.setdefault(scope, {})
        return cells

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            for cells in self._scoped.values():
                cells.clear()

    @staticmethod
    def _copy_cell(cell: Any) -> Any:
        return cell  # Counter cells are plain floats; mutable kinds override

    def _all_sorted_cells(self) -> List[Tuple[Optional[Tuple[str, ...]], Tuple[str, ...], Any]]:
        """Every cell as ``(scope_values_or_None, label_key, copied_cell)``,
        unscoped first — the exposition/snapshot surface. Cell state is
        copied while holding the lock: a scrape/snapshot concurrent with
        recording must never see a half-applied update (e.g. a histogram
        bucket bumped but its count not yet)."""
        with self._lock:
            out: List[Tuple[Optional[Tuple[str, ...]], Tuple[str, ...], Any]] = [
                (None, k, self._copy_cell(c)) for k, c in sorted(self._cells.items())
            ]
            for sv in sorted(self._scoped):
                out.extend(
                    (sv, k, self._copy_cell(c))
                    for k, c in sorted(self._scoped[sv].items())
                )
            return out

    def _full_labels(
        self, scope: Optional[Tuple[str, ...]], key: Tuple[str, ...]
    ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
        """(labelnames, labelvalues) with the scope labels prepended."""
        if scope is None:
            return self.labelnames, key
        return self._scope_labelnames + self.labelnames, scope + key

    def _has_cells(self) -> bool:
        with self._lock:
            return bool(self._cells) or any(self._scoped.values())

    def scope_labelnames(self) -> Tuple[str, ...]:
        with self._lock:
            return self._scope_labelnames

    def scopes(self) -> List[Tuple[str, ...]]:
        """Registered scope value tuples (e.g. ``[("r0",), ("r1",)]``)."""
        with self._lock:
            return sorted(self._scoped)


class _BoundCounter:
    __slots__ = ("_m", "_key", "_scope")

    def __init__(
        self, m: "Counter", key: Tuple[str, ...],
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        self._m, self._key, self._scope = m, key, scope

    def inc(self, n: float = 1.0) -> None:
        self._m._inc(self._key, n, self._scope)


class Counter(_Metric):
    """Monotonic counter; float increments allowed (e.g. seconds totals)."""

    kind = "counter"

    def labels(self, **kv: Any) -> _BoundCounter:
        return _BoundCounter(self, self._label_key(kv))

    def inc(self, n: float = 1.0) -> None:
        self._inc((), n)

    def _inc(
        self, key: Tuple[str, ...], n: float,
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        if n < 0:
            # validate before the enabled gate so a buggy call site fails in
            # metrics-off test runs, not first in a metrics-on production serve
            raise ValueError(f"counter '{self.name}' cannot decrease (inc {n})")
        if not _ENABLED[0]:
            return
        with self._lock:
            cells = self._cells_for(scope)
            cells[key] = cells.get(key, 0.0) + n

    def value(self, **kv: Any) -> float:
        key = self._label_key(kv)
        with self._lock:
            return float(self._cells.get(key, 0.0))

    def total(self) -> float:
        with self._lock:
            return float(sum(self._cells.values()))

    def scope_value(self, scope: Tuple[str, ...], **kv: Any) -> float:
        key = self._label_key(kv)
        with self._lock:
            return float(self._cells_for(tuple(scope)).get(key, 0.0))

    def scope_total(self, scope: Tuple[str, ...]) -> float:
        with self._lock:
            return float(sum(self._cells_for(tuple(scope)).values()))

    def _render(self, lines: List[str]) -> None:
        for sv, key, v in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            lines.append(f"{self.name}{_fmt_labels(names, vals)} {_fmt_value(v)}")

    def _snapshot_values(self) -> List[Dict[str, Any]]:
        out = []
        for sv, key, v in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            out.append({"labels": dict(zip(names, vals)), "value": v})
        return out


class _BoundGauge:
    __slots__ = ("_m", "_key", "_scope")

    def __init__(
        self, m: "Gauge", key: Tuple[str, ...],
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        self._m, self._key, self._scope = m, key, scope

    def set(self, v: float) -> None:
        self._m._set(self._key, v, self._scope)

    def inc(self, n: float = 1.0) -> None:
        self._m._add(self._key, n, self._scope)

    def dec(self, n: float = 1.0) -> None:
        self._m._add(self._key, -n, self._scope)


class Gauge(_Metric):
    """Point-in-time value; also tracks the high-water mark since reset
    (the ``DeviceMemoryStat`` peak/current pattern, stats.h:126)."""

    kind = "gauge"

    @staticmethod
    def _copy_cell(cell: Any) -> Any:
        return dict(cell)

    def labels(self, **kv: Any) -> _BoundGauge:
        return _BoundGauge(self, self._label_key(kv))

    def set(self, v: float) -> None:
        self._set((), v)

    def inc(self, n: float = 1.0) -> None:
        self._add((), n)

    def dec(self, n: float = 1.0) -> None:
        self._add((), -n)

    def _set(
        self, key: Tuple[str, ...], v: float,
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        if not _ENABLED[0]:
            return
        v = float(v)
        with self._lock:
            cells = self._cells_for(scope)
            cell = cells.setdefault(key, {"value": 0.0, "max": v})
            cell["value"] = v
            cell["max"] = max(cell["max"], v)

    def _add(
        self, key: Tuple[str, ...], n: float,
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        if not _ENABLED[0]:
            return
        with self._lock:
            cells = self._cells_for(scope)
            cell = cells.setdefault(key, {"value": 0.0, "max": 0.0})
            cell["value"] += float(n)
            cell["max"] = max(cell["max"], cell["value"])

    def value(self, **kv: Any) -> float:
        key = self._label_key(kv)
        with self._lock:
            cell = self._cells.get(key)
            return float(cell["value"]) if cell else 0.0

    def high_water(self, **kv: Any) -> float:
        key = self._label_key(kv)
        with self._lock:
            cell = self._cells.get(key)
            return float(cell["max"]) if cell else 0.0

    def scope_value(self, scope: Tuple[str, ...], **kv: Any) -> float:
        key = self._label_key(kv)
        with self._lock:
            cell = self._cells_for(tuple(scope)).get(key)
            return float(cell["value"]) if cell else 0.0

    def _render(self, lines: List[str]) -> None:
        for sv, key, cell in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            lines.append(
                f"{self.name}{_fmt_labels(names, vals)} {_fmt_value(cell['value'])}"
            )

    def _snapshot_values(self) -> List[Dict[str, Any]]:
        out = []
        for sv, key, cell in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            out.append(
                {"labels": dict(zip(names, vals)), "value": cell["value"], "max": cell["max"]}
            )
        return out


class _BoundHistogram:
    __slots__ = ("_m", "_key", "_scope")

    def __init__(
        self, m: "Histogram", key: Tuple[str, ...],
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        self._m, self._key, self._scope = m, key, scope

    def observe(self, v: float) -> None:
        self._m._observe(self._key, v, self._scope)


class Histogram(_Metric):
    """Fixed log-scale buckets: upper bounds ``start * factor**i`` for
    ``i < count``, plus +Inf overflow. Percentiles via linear interpolation
    inside the winning bucket (``histogram_quantile`` semantics)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str = "",
        labelnames: Sequence[str] = (),
        start: float = 1e-4,
        factor: float = 2.0,
        count: int = 26,
    ) -> None:
        super().__init__(name, help_, labelnames)
        if start <= 0 or factor <= 1 or count < 1:
            raise ValueError(f"bad log-scale bucket spec ({start}, {factor}, {count})")
        self.bucket_spec: Tuple[float, float, int] = (float(start), float(factor), int(count))
        self.bounds: Tuple[float, ...] = tuple(start * factor**i for i in range(count))

    def _new_cell(self) -> Dict[str, Any]:
        return {"counts": [0] * (len(self.bounds) + 1), "sum": 0.0, "count": 0}

    @staticmethod
    def _copy_cell(cell: Dict[str, Any]) -> Dict[str, Any]:
        return {"counts": list(cell["counts"]), "sum": cell["sum"], "count": cell["count"]}

    def labels(self, **kv: Any) -> _BoundHistogram:
        return _BoundHistogram(self, self._label_key(kv))

    def observe(self, v: float) -> None:
        self._observe((), v)

    def _observe(
        self, key: Tuple[str, ...], v: float,
        scope: Optional[Tuple[str, ...]] = None,
    ) -> None:
        if not _ENABLED[0]:
            return
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)  # first bound >= v (le semantics)
        with self._lock:
            cells = self._cells_for(scope)
            cell = cells.get(key)
            if cell is None:
                cell = cells[key] = self._new_cell()
            cell["counts"][i] += 1
            cell["sum"] += v
            cell["count"] += 1

    def _cell(
        self, kv: Dict[str, Any], scope: Optional[Tuple[str, ...]] = None
    ) -> Optional[Dict[str, Any]]:
        key = self._label_key(kv)
        with self._lock:
            cell = self._cells_for(scope).get(key)
            return self._copy_cell(cell) if cell is not None else None

    def count(self, **kv: Any) -> int:
        cell = self._cell(kv)
        return int(cell["count"]) if cell else 0

    def sum(self, **kv: Any) -> float:
        cell = self._cell(kv)
        return float(cell["sum"]) if cell else 0.0

    def bucket_counts(self, **kv: Any) -> List[int]:
        cell = self._cell(kv)
        return list(cell["counts"]) if cell else [0] * (len(self.bounds) + 1)

    def quantile(self, q: float, **kv: Any) -> float:
        """Estimate the q-quantile (0..1). Empty histogram -> 0.0; mass in
        the +Inf bucket resolves to the largest finite bound."""
        return self._quantile_of_cell(self._cell(kv), q)

    def _quantile_of_cell(self, cell: Optional[Dict[str, Any]], q: float) -> float:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if cell is None or cell["count"] == 0:
            return 0.0
        target = q * cell["count"]
        cum = 0.0
        for i, c in enumerate(cell["counts"]):
            prev = cum
            cum += c
            if cum >= target and c > 0:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i]
                return lo + (hi - lo) * (target - prev) / c
        return self.bounds[-1]

    def _render(self, lines: List[str]) -> None:
        for sv, key, cell in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            cum = 0
            for bound, c in zip(self.bounds, cell["counts"]):
                cum += c
                le = _fmt_labels(names, vals, extra=f'le="{_fmt_value(bound)}"')
                lines.append(f"{self.name}_bucket{le} {cum}")
            le = _fmt_labels(names, vals, extra='le="+Inf"')
            lines.append(f"{self.name}_bucket{le} {cell['count']}")
            base = _fmt_labels(names, vals)
            lines.append(f"{self.name}_sum{base} {_fmt_value(cell['sum'])}")
            lines.append(f"{self.name}_count{base} {cell['count']}")

    def _snapshot_values(self) -> List[Dict[str, Any]]:
        out = []
        for sv, key, cell in self._all_sorted_cells():
            names, vals = self._full_labels(sv, key)
            cum, buckets = 0, {}
            for bound, c in zip(self.bounds, cell["counts"]):
                cum += c
                buckets[_fmt_value(bound)] = cum
            buckets["+Inf"] = cell["count"]
            out.append(
                {
                    "labels": dict(zip(names, vals)),
                    "count": cell["count"],
                    "sum": cell["sum"],
                    "buckets": buckets,
                }
            )
        return out


class _ScopedCounter:
    """Scope-bound view of a :class:`Counter`: same recording API, cells
    carry the scope labels. Reads return the SCOPE's cells only."""

    __slots__ = ("_f", "_scope")
    kind = "counter"

    def __init__(self, family: Counter, scope: Tuple[str, ...]) -> None:
        self._f, self._scope = family, scope

    @property
    def name(self) -> str:
        return self._f.name

    def labels(self, **kv: Any) -> _BoundCounter:
        return _BoundCounter(self._f, self._f._label_key(kv), self._scope)

    def inc(self, n: float = 1.0) -> None:
        self._f._inc((), n, self._scope)

    def value(self, **kv: Any) -> float:
        return self._f.scope_value(self._scope, **kv)

    def total(self) -> float:
        return self._f.scope_total(self._scope)


class _ScopedGauge:
    __slots__ = ("_f", "_scope")
    kind = "gauge"

    def __init__(self, family: Gauge, scope: Tuple[str, ...]) -> None:
        self._f, self._scope = family, scope

    @property
    def name(self) -> str:
        return self._f.name

    def labels(self, **kv: Any) -> _BoundGauge:
        return _BoundGauge(self._f, self._f._label_key(kv), self._scope)

    def set(self, v: float) -> None:
        self._f._set((), v, self._scope)

    def inc(self, n: float = 1.0) -> None:
        self._f._add((), n, self._scope)

    def dec(self, n: float = 1.0) -> None:
        self._f._add((), -n, self._scope)

    def value(self, **kv: Any) -> float:
        return self._f.scope_value(self._scope, **kv)


class _ScopedHistogram:
    __slots__ = ("_f", "_scope")
    kind = "histogram"

    def __init__(self, family: Histogram, scope: Tuple[str, ...]) -> None:
        self._f, self._scope = family, scope

    @property
    def name(self) -> str:
        return self._f.name

    def labels(self, **kv: Any) -> _BoundHistogram:
        return _BoundHistogram(self._f, self._f._label_key(kv), self._scope)

    def observe(self, v: float) -> None:
        self._f._observe((), v, self._scope)

    def count(self, **kv: Any) -> int:
        cell = self._f._cell(kv, self._scope)
        return int(cell["count"]) if cell else 0

    def sum(self, **kv: Any) -> float:
        cell = self._f._cell(kv, self._scope)
        return float(cell["sum"]) if cell else 0.0

    def quantile(self, q: float, **kv: Any) -> float:
        return self._f._quantile_of_cell(self._f._cell(kv, self._scope), q)


class MetricScope:
    """One resolved label scope (e.g. ``replica="r0"``) — see the module
    docstring. Construct via :meth:`MetricsRegistry.scope`; bind whole family
    dicts at replica construction with :meth:`bind_all` so the per-record
    path never re-resolves anything."""

    __slots__ = ("labelnames", "labelvalues")

    _WRAPPERS = {}  # kind class -> scoped class; filled below

    def __init__(self, **labels: Any) -> None:
        if not labels:
            raise ValueError("a metric scope needs at least one label")
        names = tuple(sorted(labels))
        self.labelnames = names
        self.labelvalues = tuple(str(labels[n]) for n in names)

    def bind(self, family: Any) -> Any:
        """Scope-bound view of one family (Counter/Gauge/Histogram)."""
        for cls, wrapper in self._WRAPPERS.items():
            if isinstance(family, cls):
                family._register_scope(self.labelnames, self.labelvalues)
                return wrapper(family, self.labelvalues)
        raise TypeError(f"cannot scope a {type(family).__name__}")

    def bind_all(self, families: Dict[str, Any]) -> Dict[str, Any]:
        """Scope-bound copy of a ``{short_name: family}`` dict (the shape
        every instrumented component resolves at construction)."""
        return {k: self.bind(f) for k, f in families.items()}

    def __repr__(self) -> str:
        pairs = ", ".join(
            f"{n}={v!r}" for n, v in zip(self.labelnames, self.labelvalues)
        )
        return f"MetricScope({pairs})"


MetricScope._WRAPPERS = {
    Counter: _ScopedCounter,
    Gauge: _ScopedGauge,
    Histogram: _ScopedHistogram,
}


class MetricsRegistry:
    """Thread-safe get-or-create registry of metric families."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.RLock()

    def _get_or_create(self, cls: type, name: str, help_: str, labelnames: Sequence[str], **kw: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric '{name}' already registered as {m.kind} with "
                        f"labels {m.labelnames}"
                    )
                return m
            m = cls(name, help_, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, labelnames)

    def gauge(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labelnames)

    def histogram(
        self,
        name: str,
        help_: str = "",
        labelnames: Sequence[str] = (),
        start: float = 1e-4,
        factor: float = 2.0,
        count: int = 26,
    ) -> Histogram:
        h = self._get_or_create(
            Histogram, name, help_, labelnames, start=start, factor=factor, count=count
        )
        spec = (float(start), float(factor), int(count))
        if h.bucket_spec != spec:
            raise ValueError(
                f"histogram '{name}' already registered with buckets "
                f"{h.bucket_spec}, requested {spec}"
            )
        return h

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def family(self, name: str) -> _Metric:
        """Strict read-by-name: the registered family, or ``KeyError``.
        Aggregation/healthz/snapshot consumers must use this (not
        :meth:`get`) so a typo'd family name fails loudly instead of
        silently reading zeros — analyzer check OB602 statically validates
        every literal name passed here against the package's registered
        families."""
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            raise KeyError(f"no metric family named '{name}' is registered")
        return m

    def scope(self, **labels: Any) -> MetricScope:
        """Resolve a label scope once (e.g. ``registry.scope(replica="r0")``
        at replica construction); bind families through it for replica-
        attributed recording."""
        return MetricScope(**labels)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Zero every metric's cells; definitions survive (tests, bench)."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable view of every non-empty metric family."""
        out: Dict[str, Any] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            values = m._snapshot_values()
            if values:
                out[m.name] = {"type": m.kind, "help": m.help, "values": values}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            if not m._has_cells():
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            m._render(lines)
        return "\n".join(lines) + "\n" if lines else ""


GLOBAL_METRICS = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return GLOBAL_METRICS
