"""Error taxonomy + enforce helpers.

Counterpart of the reference's ``paddle/common/errors.h`` error-type taxonomy and
``paddle/common/enforce.h`` PADDLE_ENFORCE macros: typed exceptions with
actionable messages, and small check helpers used across the framework.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence


class PaddleTpuError(Exception):
    """Base class for all framework errors."""


class InvalidArgumentError(PaddleTpuError, ValueError):
    pass


class NotFoundError(PaddleTpuError, KeyError):
    pass


class OutOfRangeError(PaddleTpuError, IndexError):
    pass


class AlreadyExistsError(PaddleTpuError):
    pass


class PreconditionNotMetError(PaddleTpuError, RuntimeError):
    pass


class UnimplementedError(PaddleTpuError, NotImplementedError):
    pass


class UnavailableError(PaddleTpuError, RuntimeError):
    pass


class ExecutionTimeoutError(PaddleTpuError, TimeoutError):
    pass


def enforce(cond: Any, msg: str, exc: type = InvalidArgumentError) -> None:
    if not cond:
        raise exc(msg)


def enforce_eq(a: Any, b: Any, what: str = "value") -> None:
    if a != b:
        raise InvalidArgumentError(f"expected {what} == {b!r}, got {a!r}")


def enforce_in(value: Any, allowed: Sequence[Any], what: str = "value") -> None:
    if value not in allowed:
        raise InvalidArgumentError(f"expected {what} in {list(allowed)!r}, got {value!r}")


def enforce_shape_rank(shape: Sequence[int], rank: int, what: str = "tensor") -> None:
    if len(shape) != rank:
        raise InvalidArgumentError(f"expected {what} of rank {rank}, got shape {tuple(shape)}")
