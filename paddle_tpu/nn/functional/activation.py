"""Activation functions (reference ``python/paddle/nn/functional/activation.py``
over PHI activation kernels; all fuse into adjacent matmuls under XLA)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.math import sigmoid, tanh  # noqa: F401 - re-exported
from paddle_tpu.ops.registry import defop

__all__ = [
    "relu",
    "relu6",
    "gelu",
    "silu",
    "swish",
    "sigmoid",
    "tanh",
    "softmax",
    "log_softmax",
    "softplus",
    "softsign",
    "softshrink",
    "hardshrink",
    "hardsigmoid",
    "hardswish",
    "hardtanh",
    "leaky_relu",
    "elu",
    "celu",
    "selu",
    "prelu",
    "rrelu",
    "mish",
    "tanhshrink",
    "thresholded_relu",
    "log_sigmoid",
    "maxout",
    "glu",
    "swiglu",
    "gumbel_softmax",
]


@defop("relu", inplace_method="relu_")
def relu(x):
    return jax.nn.relu(x)


@defop("relu6")
def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


@defop("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defop("silu")
def silu(x):
    return jax.nn.silu(x)


@defop("swish")
def swish(x):
    return jax.nn.silu(x)


@defop("softmax_fn", tensor_method="softmax")
def softmax(x, axis=-1, dtype=None):
    from paddle_tpu.core.dtypes import convert_dtype

    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=int(axis))


@defop("log_softmax")
def log_softmax(x, axis=-1, dtype=None):
    from paddle_tpu.core.dtypes import convert_dtype

    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=int(axis))


@defop("softplus")
def softplus(x, beta=1.0, threshold=20.0):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@defop("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@defop("softshrink")
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


@defop("hardshrink")
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop("hardsigmoid")
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop("hardswish")
def hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@defop("hardtanh")
def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


@defop("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@defop("elu", inplace_method="elu_")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@defop("celu")
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@defop("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop("prelu")
def prelu(x, weight, data_format="NCHW"):
    w = weight
    if w.ndim == 1 and x.ndim > 1 and w.shape[0] != 1:
        ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@defop("rrelu")
def rrelu(x, lower=0.125, upper=0.3333333, training=True):
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@defop("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop("tanhshrink")
def tanhshrink(x):
    return x - jnp.tanh(x)


@defop("thresholded_relu")
def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


@defop("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@defop("maxout")
def maxout(x, groups, axis=1):
    axis = axis % x.ndim
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@defop("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defop("swiglu")
def swiglu(x, y=None):
    """SwiGLU (reference ``ops.yaml:4596 swiglu``; LLM MLP gate). With one
    input, splits it in half along the last dim."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@defop("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    import paddle_tpu.core.rng as _rng

    g = jax.random.gumbel(_rng.next_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis)
        one_hot = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
        # straight-through estimator: hard forward, soft backward
        y = one_hot - jax.lax.stop_gradient(y) + y
    return y
