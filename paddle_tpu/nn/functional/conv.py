"""Convolution / pooling functional ops.

Reference: ``python/paddle/nn/functional/conv.py`` + ``pooling.py`` over PHI
conv kernels (cuDNN). On TPU, ``lax.conv_general_dilated`` lowers straight to
MXU convolutions; XLA picks layouts, so both NCHW (paddle default) and NHWC
are accepted.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import defop

__all__ = [
    "conv1d",
    "conv2d",
    "conv3d",
    "conv1d_transpose",
    "conv2d_transpose",
    "conv3d_transpose",
    "max_pool1d",
    "max_pool2d",
    "max_pool3d",
    "avg_pool1d",
    "avg_pool2d",
    "avg_pool3d",
    "adaptive_avg_pool1d",
    "adaptive_avg_pool2d",
    "adaptive_avg_pool3d",
    "adaptive_max_pool1d",
    "adaptive_max_pool2d",
    "adaptive_max_pool3d",
    "interpolate",
    "upsample",
]


def _tuple(v: Any, n: int) -> tuple:
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # paddle pads as [before, after] pairs flattened
            return tuple(int(x) for x in v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding(padding: Any, n: int) -> Any:
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    p = _tuple(padding, n)
    return [(x, x) for x in p]


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    spatial = "DHW"[3 - n :]
    if data_format in (f"NC{spatial}", "NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + spatial
    else:
        lhs_spec = "N" + spatial + "C"
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs_spec, "OI" + spatial, lhs_spec)
    )
    out = jax.lax.conv_general_dilated(
        x,
        weight,
        window_strides=_tuple(stride, n),
        padding=_padding(padding, n),
        rhs_dilation=_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        ch_axis = 1 if lhs_spec.startswith("NC") else out.ndim - 1
        shape = [1] * out.ndim
        shape[ch_axis] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@defop("conv1d", tensor_method=None)
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


@defop("conv2d", tensor_method=None)
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


@defop("conv3d", tensor_method=None)
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n, data_format):
    spatial = "DHW"[3 - n :]
    lhs_spec = "NC" + spatial if data_format.startswith("NC") else "N" + spatial + "C"
    # weight layout [in, out/groups, *k] (paddle conv_transpose convention)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs_spec, "IO" + spatial, lhs_spec)
    )
    pad = _padding(padding, n)
    if isinstance(pad, str):
        pad_cfg: Any = pad
    else:
        # transpose conv: effective padding = k - 1 - p on each side
        ks = weight.shape[2:]
        dil = _tuple(dilation, n)
        pad_cfg = [
            (dil[i] * (ks[i] - 1) - pad[i][0], dil[i] * (ks[i] - 1) - pad[i][1])
            for i in range(n)
        ]
    out = jax.lax.conv_general_dilated(
        x,
        weight,
        window_strides=(1,) * n,
        padding=pad_cfg,
        lhs_dilation=_tuple(stride, n),
        rhs_dilation=_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
        transpose_kernel=True,
    )
    opad = _tuple(output_padding, n)
    if any(opad):
        width = [(0, 0)] * 2 + [(0, p) for p in opad] if lhs_spec.startswith("NC") else [(0, 0)] + [(0, p) for p in opad] + [(0, 0)]
        out = jnp.pad(out, width)
    if bias is not None:
        ch_axis = 1 if lhs_spec.startswith("NC") else out.ndim - 1
        shape = [1] * out.ndim
        shape[ch_axis] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@defop("conv1d_transpose", tensor_method=None)
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format)


@defop("conv2d_transpose", tensor_method=None)
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format)


@defop("conv3d_transpose", tensor_method=None)
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format)


def _pool(x, kernel, stride, padding, n, data_format, reducer, init, ceil_mode=False, average=False, exclusive=True):
    ks = _tuple(kernel, n)
    st = _tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    nc_layout = data_format.startswith("NC")
    if nc_layout:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + (pad if not isinstance(pad, str) else [])
    else:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + (pad if not isinstance(pad, str) else []) + [(0, 0)]
    pad_cfg = pad if isinstance(pad, str) else pads
    out = jax.lax.reduce_window(x, init, reducer, window, strides, pad_cfg)
    if average:
        if exclusive and (not isinstance(pad, str)) and any(p != (0, 0) for p in pad):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_cfg)
            out = out / counts
        else:
            out = out / float(np.prod(ks))
    return out


@defop("max_pool2d", tensor_method=None)
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.max, -jnp.inf)


@defop("max_pool1d", tensor_method=None)
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL"):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.max, -jnp.inf)


@defop("max_pool3d", tensor_method=None)
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.max, -jnp.inf)


@defop("avg_pool2d", tensor_method=None)
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add, 0.0, average=True, exclusive=exclusive)


@defop("avg_pool1d", tensor_method=None)
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL"):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.add, 0.0, average=True, exclusive=exclusive)


@defop("avg_pool3d", tensor_method=None)
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add, 0.0, average=True, exclusive=exclusive)


def _adaptive_pool(x, output_size, n, data_format, op):
    os_ = _tuple(output_size, n)
    nc_layout = data_format.startswith("NC")
    spatial_dims = list(range(2, 2 + n)) if nc_layout else list(range(1, 1 + n))
    out = x
    for dim, target in zip(spatial_dims, os_):
        size = out.shape[dim]
        if size % target != 0:
            # general case (covers upsampling, target > size): window i reads
            # inputs [floor(i*size/target), ceil((i+1)*size/target)) — never
            # empty, matching paddle/torch adaptive-pool semantics
            i = np.arange(target)
            starts = (i * size) // target
            ends = np.maximum(-(-((i + 1) * size) // target), starts + 1)
            j = np.arange(size)[:, None]
            member = (j >= starts[None, :]) & (j < ends[None, :])  # [size, target]
            one_hot = jnp.asarray(member, out.dtype)
            moved = jnp.moveaxis(out, dim, -1)
            if op == "avg":
                counts = jnp.asarray(member.sum(0), out.dtype)
                red = jnp.matmul(moved, one_hot) / counts
            else:
                red = jnp.max(
                    jnp.where(
                        one_hot.T[(None,) * (moved.ndim - 1)] > 0,
                        moved[..., None, :],
                        -jnp.inf,
                    ),
                    axis=-1,
                )
            out = jnp.moveaxis(red, -1, dim)
        else:
            k = size // target
            new_shape = list(out.shape)
            new_shape[dim : dim + 1] = [target, k]
            reshaped = out.reshape(new_shape)
            out = jnp.max(reshaped, axis=dim + 1) if op == "max" else jnp.mean(reshaped, axis=dim + 1)
    return out


@defop("adaptive_avg_pool2d", tensor_method=None)
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


@defop("adaptive_avg_pool1d", tensor_method=None)
def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return _adaptive_pool(x, output_size, 1, data_format, "avg")


@defop("adaptive_avg_pool3d", tensor_method=None)
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


@defop("adaptive_max_pool2d", tensor_method=None)
def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, "max")


@defop("adaptive_max_pool1d", tensor_method=None)
def adaptive_max_pool1d(x, output_size, data_format="NCL"):
    return _adaptive_pool(x, output_size, 1, data_format, "max")


@defop("adaptive_max_pool3d", tensor_method=None)
def adaptive_max_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, "max")


@defop("interpolate_fn", tensor_method=None)
def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    data_format="NCHW",
):
    nc_layout = data_format.startswith("NC")
    n_spatial = x.ndim - 2
    in_spatial = x.shape[2:] if nc_layout else x.shape[1:-1]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * n_spatial
        size = [int(round(s * f)) for s, f in zip(in_spatial, sf)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * n_spatial)]
    if nc_layout:
        target_shape = (x.shape[0], x.shape[1], *size)
    else:
        target_shape = (x.shape[0], *size, x.shape[-1])
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]
    return jax.image.resize(x, target_shape, method=method)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode, align_corners=align_corners, data_format=data_format)
