"""Common nn functional ops: linear, embedding, dropout, norm layers, one_hot…

Reference: ``python/paddle/nn/functional/common.py`` / ``input.py`` / ``norm.py``
over PHI kernels (``layer_norm``, ``rms_norm``, ``embedding``, ``dropout``).
On TPU all of these are XLA-fused elementwise/reduction graphs; rms_norm also
has a Pallas fast path (see ``paddle_tpu.kernels``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

import paddle_tpu.core.rng as _rng
from paddle_tpu.ops.registry import defop

__all__ = [
    "linear",
    "weight_only_linear",
    "embedding",
    "one_hot",
    "dropout",
    "dropout2d",
    "dropout3d",
    "alpha_dropout",
    "layer_norm",
    "rms_norm",
    "group_norm",
    "instance_norm",
    "batch_norm",
    "local_response_norm",
    "normalize",
    "cosine_similarity",
    "pixel_shuffle",
    "pixel_unshuffle",
    "channel_shuffle",
    "unfold",
    "fold",
    "bilinear",
    "label_smooth",
]


@defop("linear", tensor_method=None)
def linear(x, weight, bias=None):
    """y = x @ W (+ b). Weight layout [in, out] (paddle convention, reference
    ``python/paddle/nn/functional/common.py`` linear)."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@defop("weight_only_linear", tensor_method=None)
def weight_only_linear(x, weight, weight_scale, bias=None):
    """y = x @ dequant(W) (+ b) with W stored int8 and per-output-channel
    fp32 scales (reference ``paddle.nn.quant.weight_only_linear``). The
    dequant happens inside the matmul (``kernels.quant.int8_weight_matmul``)
    — a bf16 copy of the weight never materializes. Inference-only."""
    from paddle_tpu.kernels.quant import int8_weight_matmul

    out = int8_weight_matmul(x, weight, weight_scale)
    if bias is not None:
        out = out + bias
    return out


@defop("embedding_fn", tensor_method=None)
def embedding(x, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


@defop("one_hot", tensor_method=None)
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def _dropout_impl(x, p, training, mode, key, broadcast_dims=()):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    mask_shape = list(x.shape)
    for d in broadcast_dims:
        mask_shape[d] = 1
    mask = jax.random.bernoulli(key, keep, tuple(mask_shape))
    if mode in ("upscale_in_train", "dropout"):
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
    # downscale_in_infer: scale at inference instead (train applies raw mask)
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


@defop("dropout_fn", tensor_method=None)
def _dropout_op(x, key, p=0.5, training=True, mode="upscale_in_train", broadcast_dims=()):
    return _dropout_impl(x, p, training, mode, key, broadcast_dims)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x
    bdims = ()
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ndim = x.ndim
        bdims = tuple(d for d in range(ndim) if d not in [a % ndim for a in axes])
    return _dropout_op(x, _rng.next_key(), p=p, training=training, mode=mode, broadcast_dims=bdims)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    bdims = (2, 3) if data_format == "NCHW" else (1, 2)
    return _dropout_op(x, _rng.next_key(), p=p, training=training, broadcast_dims=bdims)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    bdims = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    return _dropout_op(x, _rng.next_key(), p=p, training=training, broadcast_dims=bdims)


@defop("alpha_dropout_fn", tensor_method=None)
def _alpha_dropout_op(x, key, p=0.5, training=True):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p**2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return a * jnp.where(mask, x, alpha_p) + b


def alpha_dropout(x, p=0.5, training=True, name=None):
    return _alpha_dropout_op(x, _rng.next_key(), p=p, training=training)


@defop("layer_norm_fn", tensor_method=None)
def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    if normalized_shape is None:
        axes = (x.ndim - 1,)
    else:
        n = len(normalized_shape) if isinstance(normalized_shape, (list, tuple)) else 1
        axes = tuple(range(x.ndim - n, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop("rms_norm_fn", tensor_method=None)
def rms_norm(x, weight=None, epsilon=1e-6, upcast=True):
    """RMSNorm (reference fused ``rms_norm`` kernel,
    ``paddle/phi/kernels/gpu/rms_norm_kernel``): compute in fp32, scale, cast
    back — numerics match the fused GPU kernel's accumulate-in-float behavior.
    On TPU the Pallas fused kernel pins the single-HBM-round-trip schedule."""
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    if (
        weight is not None
        and upcast  # kernel always accumulates fp32
        and weight.dtype == x.dtype  # kernel returns x.dtype; no promotion
        and x.shape[-1] % 128 == 0  # lane-aligned → guaranteed lowerable
        and pallas_enabled("use_pallas_fused")
    ):
        try:
            from paddle_tpu.kernels.fused import fused_rms_norm_pallas

            return fused_rms_norm_pallas(x, weight, epsilon)
        except Exception as exc:  # pragma: no cover - TPU-only path
            warn_fallback("fused_rms_norm", exc)
    dtype = x.dtype
    if upcast:
        x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + epsilon)
    out = out.astype(dtype)
    if weight is not None:
        out = out * weight
    return out


@defop("group_norm_fn", tensor_method=None)
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    g = x.reshape(n, num_groups, c // num_groups, *spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(g - mean), axis=axes, keepdims=True)
    out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    if weight is not None:
        out = out * weight.reshape(1, c, *([1] * len(spatial)))
    if bias is not None:
        out = out + bias.reshape(1, c, *([1] * len(spatial)))
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop("instance_norm_fn", tensor_method=None)
def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    c = x.shape[1]
    if weight is not None:
        out = out * weight.reshape(1, c, *([1] * (x.ndim - 2)))
    if bias is not None:
        out = out + bias.reshape(1, c, *([1] * (x.ndim - 2)))
    return out


@defop("batch_norm_fn", tensor_method=None)
def _batch_norm_op(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    epsilon=1e-5,
    data_format="NCHW",
):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(d for d in range(x.ndim) if d != ch_axis)
    if training:
        mean = jnp.mean(x, axis=reduce_axes)
        var = jnp.var(x, axis=reduce_axes)
    else:
        mean, var = running_mean, running_var
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    name=None,
):
    """Functional batch norm; updates running stats in-place when training
    (matching the reference's mutable running-stat semantics)."""
    import paddle_tpu

    out, mean, var = _batch_norm_op(
        x, running_mean, running_var, weight, bias, training=training,
        epsilon=epsilon, data_format=data_format,
    )
    if training and hasattr(running_mean, "set_value"):
        with paddle_tpu.no_grad():
            running_mean.set_value(momentum * running_mean.data + (1 - momentum) * mean.detach().data)
            running_var.set_value(momentum * running_var.data + (1 - momentum) * var.detach().data)
    return out


@defop("local_response_norm_fn", tensor_method=None)
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
    ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
    sq = jnp.square(x)
    moved = jnp.moveaxis(sq, ch_axis, -1)
    pad = [(0, 0)] * (moved.ndim - 1) + [(size // 2, (size - 1) // 2)]
    padded = jnp.pad(moved, pad)
    window = sum(padded[..., i : i + moved.shape[-1]] for i in range(size))
    denom = jnp.power(k + alpha * window / size, beta)
    return x / jnp.moveaxis(denom, -1, ch_axis)


@defop("normalize_fn", tensor_method=None)
def normalize(x, p=2.0, axis=1, epsilon=1e-12):
    n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p)
    return x / jnp.maximum(n, epsilon)


@defop("cosine_similarity_fn", tensor_method=None)
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@defop("pixel_shuffle_fn", tensor_method=None)
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


@defop("pixel_unshuffle_fn", tensor_method=None)
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h // r, w // r, c * r * r)


@defop("channel_shuffle_fn", tensor_method=None)
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.transpose(x, (0, 2, 1, 3, 4))
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.transpose(x, (0, 1, 2, 4, 3))
    return x.reshape(n, h, w, c)


@defop("unfold_fn", tensor_method=None)
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=tuple(ks),
        window_strides=tuple(st),
        padding=[(pd[0], pd[0]), (pd[1], pd[1])],
        rhs_dilation=tuple(dl),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return patches.reshape(n, c * ks[0] * ks[1], -1)


@defop("fold_fn", tensor_method=None)
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    n, ckk, l = x.shape
    c = ckk // (ks[0] * ks[1])
    oh = (os_[0] + 2 * pd[0] - ks[0]) // st[0] + 1
    ow = (os_[1] + 2 * pd[1] - ks[1]) // st[1] + 1
    cols = x.reshape(n, c, ks[0], ks[1], oh, ow)
    out = jnp.zeros((n, c, os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]), x.dtype)
    for i in range(ks[0]):
        for j in range(ks[1]):
            out = out.at[
                :, :, i : i + oh * st[0] : st[0], j : j + ow * st[1] : st[1]
            ].add(cols[:, :, i, j])
    return out[:, :, pd[0] : pd[0] + os_[0], pd[1] : pd[1] + os_[1]]


@defop("bilinear_fn", tensor_method=None)
def bilinear(x1, x2, weight, bias=None):
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@defop("label_smooth_fn", tensor_method=None)
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k
