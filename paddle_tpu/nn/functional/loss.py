"""Loss functional ops (reference ``python/paddle/nn/functional/loss.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import defop

__all__ = [
    "cross_entropy",
    "fused_linear_cross_entropy",
    "softmax_with_cross_entropy",
    "nll_loss",
    "mse_loss",
    "l1_loss",
    "smooth_l1_loss",
    "binary_cross_entropy",
    "binary_cross_entropy_with_logits",
    "kl_div",
    "margin_ranking_loss",
    "cosine_embedding_loss",
    "triplet_margin_loss",
    "hinge_embedding_loss",
    "log_loss",
    "square_error_cost",
    "ctc_loss",
    "sigmoid_focal_loss",
]


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


@defop("cross_entropy_fn", tensor_method=None)
def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
):
    """Softmax cross entropy (reference ``cross_entropy_with_softmax`` kernel +
    ``python/paddle/nn/functional/loss.py`` cross_entropy)."""
    logits = input
    if jnp.issubdtype(logits.dtype, jnp.floating) and jnp.finfo(logits.dtype).bits < 32:
        # fp32 logsumexp accumulation for half-precision callers: the upcast
        # fuses into the jitted log_softmax instead of forcing call sites to
        # pre-materialize (and pin across backward) an fp32 [.., V] copy
        logits = logits.astype(jnp.float32)
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
    if soft_label:
        target = label
        if label_smoothing > 0.0:
            k = logp.shape[axis]
            target = (1 - label_smoothing) * target + label_smoothing / k
        loss = -jnp.sum(target * logp, axis=axis)
        if weight is not None:
            loss = loss * jnp.sum(target * weight, axis=axis)
        return _reduce(loss, reduction)
    lbl = label
    if lbl.ndim == logp.ndim:
        lbl = jnp.squeeze(lbl, axis=axis)
    lbl = lbl.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    if label_smoothing > 0.0:
        k = logp.shape[axis]
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=axis)[..., 0]
        smooth = -jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * nll + label_smoothing * smooth
    else:
        loss = -jnp.take_along_axis(logp, safe[..., None], axis=axis)[..., 0]
    if weight is not None:
        w = weight[safe]
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if weight is not None:
            denom = jnp.sum(jnp.where(valid, weight[safe], 0.0))
        else:
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@defop("fused_linear_cross_entropy_fn", tensor_method=None)
def fused_linear_cross_entropy(
    input,  # noqa: A002
    weight,
    label,
    ignore_index=-100,
    reduction="mean",
    weight_vocab_major=False,
    weight_scale=None,
):
    """Fused lm-head + softmax cross entropy: ``cross_entropy(input @ Wᵀ,
    label)`` computed vocab-chunk-wise so the ``[.., V]`` logits are never
    materialized in any dtype (forward keeps an online fp32 logsumexp + the
    target-class logit; backward recomputes block logits — see
    ``kernels/fused_loss.py``). ``weight`` is ``[H, V]`` (``nn.Linear``
    layout) or ``[V, H]`` with ``weight_vocab_major=True`` (tied-embedding
    lm-head). Loss is fp32; ``ignore_index`` / ``reduction`` semantics match
    :func:`cross_entropy`. Pallas on TPU (``FLAGS_use_fused_loss``), a
    ``lax.scan`` reference with the same custom-VJP decomposition elsewhere.
    """
    from paddle_tpu.kernels.fused_loss import fused_linear_cross_entropy as _fused

    return _fused(
        input,
        weight,
        label,
        ignore_index=ignore_index,
        reduction=reduction,
        vocab_major=weight_vocab_major,
        weight_scale=weight_scale,
    )


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    axis=-1,
):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis
    )
    if loss.ndim < logits.ndim:
        from paddle_tpu.ops.manipulation import unsqueeze

        loss = unsqueeze(loss, axis)
    if return_softmax:
        from paddle_tpu.nn.functional.activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


@defop("nll_loss_fn", tensor_method=None)
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    loss = -jnp.take_along_axis(input, safe[..., None] if input.ndim == lbl.ndim + 1 else safe, axis=1 if input.ndim > 1 else 0)
    if input.ndim == lbl.ndim + 1:
        loss = jnp.squeeze(loss, axis=1)
    if weight is not None:
        loss = loss * weight[safe]
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.sum(weight[safe] * valid) if weight is not None else jnp.maximum(jnp.sum(valid), 1)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@defop("mse_loss_fn", tensor_method=None)
def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@defop("l1_loss_fn", tensor_method=None)
def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop("smooth_l1_loss_fn", tensor_method=None)
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * jnp.square(d) / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@defop("binary_cross_entropy_fn", tensor_method=None)
def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, 1.0)) + (1 - label) * jnp.log(jnp.clip(1 - input, eps, 1.0)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop("binary_cross_entropy_with_logits_fn", tensor_method=None)
def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop("kl_div_fn", tensor_method=None)
def kl_div(input, label, reduction="mean", log_target=False):  # noqa: A002
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        safe_label = jnp.clip(label, 1e-12, None)
        loss = label * (jnp.log(safe_label) - input)
        loss = jnp.where(label > 0, loss, 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop("margin_ranking_loss_fn", tensor_method=None)
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


@defop("cosine_embedding_loss_fn", tensor_method=None)
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1) + 1e-12
    )
    loss = jnp.where(label == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
    return _reduce(loss, reduction)


@defop("triplet_margin_loss_fn", tensor_method=None)
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, eps=1e-6, swap=False, reduction="mean"):  # noqa: A002
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b + eps), p), axis=-1), 1.0 / p)

    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.clip(d_pos - d_neg + margin, 0, None)
    return _reduce(loss, reduction)


@defop("hinge_embedding_loss_fn", tensor_method=None)
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    loss = jnp.where(label == 1, input, jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


@defop("log_loss_fn", tensor_method=None)
def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)


@defop("square_error_cost_fn", tensor_method=None)
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


@defop("sigmoid_focal_loss_fn", tensor_method=None)
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + jnp.clip(-logit, 0, None)
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        alpha_t = alpha * label + (1 - alpha) * (1 - label)
        loss = alpha_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@defop("ctc_loss_fn", tensor_method=None)
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC forward algorithm in log space via lax.scan (reference warpctc
    third_party dependency replaced by a pure-XLA implementation)."""
    # log_probs: [T, B, C] (paddle layout: max_logit_length, batch, classes)
    T, B, C = log_probs.shape
    S = labels.shape[1]  # max label length
    # extended labels with blanks: [B, 2S+1]
    ext = jnp.full((B, 2 * S + 1), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths + 1

    neg_inf = -1e30
    # alpha init at t=0
    lp0 = log_probs[0]  # [B, C]
    alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(lp0[jnp.arange(B), ext[:, 0]])
    if S > 0:
        alpha0 = alpha0.at[:, 1].set(jnp.where(ext_len > 1, lp0[jnp.arange(B), ext[:, 1]], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )

    def step(alpha, lp):
        prev1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(same_as_prev2, neg_inf, prev2)
        merged = jnp.logaddexp(alpha, jnp.logaddexp(prev1, prev2))
        emit = jnp.take_along_axis(lp, ext, axis=1)
        return merged + emit, None

    def masked_step(carry, inputs):
        alpha, t = carry
        lp = inputs
        new_alpha, _ = step(alpha, lp)
        keep = (t + 1) < input_lengths  # [B]
        alpha = jnp.where(keep[:, None], new_alpha, alpha)
        return (alpha, t + 1), None

    (alpha, _), _ = jax.lax.scan(masked_step, (alpha0, jnp.zeros((), jnp.int32)), log_probs[1:])
    b_idx = jnp.arange(B)
    last = alpha[b_idx, ext_len - 1]
    last2 = jnp.where(ext_len - 2 >= 0, alpha[b_idx, jnp.clip(ext_len - 2, 0, None)], neg_inf)
    ll = jnp.logaddexp(last, last2)
    loss = -ll
    if reduction == "mean":
        return jnp.mean(loss / label_lengths.astype(loss.dtype))
    return _reduce(loss, reduction)
