"""Attention APIs: flash_attention, scaled_dot_product_attention, and the
FlashMask sparse-mask variant.

Reference surface: ``python/paddle/nn/functional/flash_attention.py`` —
``flash_attention:195``, ``scaled_dot_product_attention:976``,
``flashmask_attention:1098`` (the fork's marquee feature: column-sparse mask
encoding via ``startend_row_indices [B, H, S, {1,2,4}]`` giving O(S) mask
memory; kernel plumbing ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:353``).

On TPU the fast path is a Pallas flash-attention kernel
(``paddle_tpu.kernels.flash_attention``); this module provides the API surface,
mask semantics, and an XLA fallback that XLA fuses reasonably well. The
Pallas path is selected by ``FLAGS_use_pallas_attention`` when running on TPU
with supported shapes/dtypes.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.ops.registry import defop

__all__ = [
    "flash_attention",
    "scaled_dot_product_attention",
    "flashmask_attention",
    "flash_attn_unpadded",
    "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked",
    "sdp_kernel",
]


def _use_pallas(q) -> bool:
    from paddle_tpu.kernels.select import pallas_enabled

    # pre-trace applicability: Mosaic-lowerable head dim (64-lane aligned) —
    # checked BEFORE tracing because a lowering failure inside a captured
    # train step cannot fall back (see kernels/select.py)
    if q.shape[-1] % 64 != 0:
        return False
    return pallas_enabled("use_pallas_attention")


def _xla_attention(q, k, v, bias=None, causal=False, scale=None, window=None, dropout=0.0, dropout_key=None):
    """Reference attention in XLA ops. Layout: [B, S, H, D] (paddle flash
    attention layout). Computes in fp32 for softmax stability."""
    in_dtype = q.dtype
    d = q.shape[-1]
    scale = scale if scale is not None else (1.0 / (d**0.5))
    # [B, H, S, D]
    qh = jnp.moveaxis(q, 2, 1).astype(jnp.float32)
    kh = jnp.moveaxis(k, 2, 1).astype(jnp.float32)
    vh = jnp.moveaxis(v, 2, 1).astype(jnp.float32)
    # grouped-query attention: repeat kv heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    sq, sk = logits.shape[-2], logits.shape[-1]
    neg = jnp.asarray(-1e30, jnp.float32)
    if causal:
        row = jnp.arange(sq)[:, None] + (sk - sq)
        col = jnp.arange(sk)[None, :]
        logits = jnp.where(col <= row, logits, neg)
    if window is not None:
        left, right = window
        row = jnp.arange(sq)[:, None] + (sk - sq)
        col = jnp.arange(sk)[None, :]
        ok = jnp.ones((sq, sk), bool)
        if left is not None and left >= 0:
            ok = ok & (col >= row - left)
        if right is not None and right >= 0:
            ok = ok & (col <= row + right)
        logits = jnp.where(ok, logits, neg)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.moveaxis(out, 1, 2).astype(in_dtype)


@defop("flash_attention", tensor_method=None)
def _flash_attention_op(q, k, v, key=None, dropout=0.0, causal=False, scale=None):
    if _use_pallas(q) and dropout == 0.0:
        try:
            from paddle_tpu.kernels.flash_attention import flash_attention_pallas

            return flash_attention_pallas(q, k, v, causal=causal, scale=scale)
        except Exception as exc:  # pragma: no cover - TPU-only path
            from paddle_tpu.kernels.select import warn_fallback

            warn_fallback("flash_attention", exc)
    return _xla_attention(q, k, v, causal=causal, scale=scale, dropout=dropout, dropout_key=key)


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """``paddle.nn.functional.flash_attention.flash_attention`` parity.

    Layout [batch, seqlen, num_heads, head_dim]; returns (out, softmax) tuple
    like the reference (softmax is None unless return_softmax).
    """
    import paddle_tpu.core.rng as _rng

    drop_key = _rng.next_key() if (dropout > 0.0 and training) else None
    out = _flash_attention_op(
        query, key, value, drop_key, dropout=dropout if training else 0.0, causal=causal
    )
    if return_softmax:
        return out, None
    return out, None


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """``scaled_dot_product_attention`` parity (reference ``flash_attention.py:976``).

    attn_mask: broadcastable additive mask [B, H, Sq, Sk] (or boolean where
    True = keep, matching paddle semantics for bool masks).
    """

    import paddle_tpu.core.rng as _rng

    drop_key = _rng.next_key() if (dropout_p > 0.0 and training) else None

    def _impl(q, k, v, mask, dkey):
        bias = None
        if mask is not None:
            if mask.dtype == jnp.bool_:
                bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
            else:
                bias = mask
        return _xla_attention(
            q, k, v, bias=bias, causal=is_causal,
            dropout=dropout_p if training else 0.0, dropout_key=dkey,
        )

    from paddle_tpu.core.dispatch import call_op

    return call_op("scaled_dot_product_attention", _impl, query, key, value, attn_mask, drop_key)


def flash_attn_unpadded(
    query,
    key,
    value,
    cu_seqlens_q,
    cu_seqlens_k,
    max_seqlen_q,
    max_seqlen_k,
    scale=1.0,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """Varlen attention (reference ``flash_attn_unpadded:593``): packed
    [total_tokens, H, D] with cu_seqlens prefix sums. Implemented via a
    document-mask attention over the packed layout — the same trick FlashMask
    encodes sparsely."""
    from paddle_tpu.core.dispatch import call_op

    def _impl(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        total_k = k.shape[0]
        # segment ids from cu_seqlens
        seg_q = jnp.cumsum(
            jnp.zeros(total_q, jnp.int32).at[cu_q[1:-1]].add(1)
        )
        seg_k = jnp.cumsum(
            jnp.zeros(total_k, jnp.int32).at[cu_k[1:-1]].add(1)
        )
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(cu_k, seg_k)
            mask = mask & (pos_k[None, :] <= pos_q[:, None])
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        vf = v.astype(jnp.float32)
        logits = jnp.einsum("qhd,khd->hqk", qf, kf) * scale
        logits = jnp.where(mask[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("hqk,khd->qhd", probs, vf)
        return out.astype(q.dtype)

    out = call_op("flash_attn_unpadded", _impl, query, key, value, cu_seqlens_q, cu_seqlens_k)
    return out, None


def flashmask_attention(
    query,
    key,
    value,
    startend_row_indices=None,
    dropout=0.0,
    causal=True,
    window_size=None,
    return_softmax_lse=False,
    return_seed_offset=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """FlashMask attention (reference ``flash_attention.py:1098`` +
    ``flash_attn_kernel.cu:353-460``).

    ``startend_row_indices``: int32 [B, H_mask, Sk, C] with C in {1, 2, 4}
    column-sparse mask encoding. For column j (a key position), the entries
    give row bounds (query positions) that are masked out:

    - C == 1, causal: rows in [start_j, Sq) are masked (downward mask; e.g.
      document masks for packed sequences).
    - C == 2, causal: rows in [start_j, end_j) are masked (e.g. sliding window
      / doc mask with global tokens).
    - C == 4, non-causal or full form: [LTS, LTE, UTS, UTE] — lower-triangle
      rows in [LTS, LTE) masked, upper-triangle rows in [UTS, UTE) masked.

    H_mask may be 1 (broadcast over heads) or num_heads.
    """
    if startend_row_indices is None:
        return flash_attention(query, key, value, dropout=dropout, causal=causal)[0]

    from paddle_tpu.core.dispatch import call_op

    def _impl(q, k, v, idx):
        if _use_pallas(q):
            try:
                from paddle_tpu.kernels.flashmask import flashmask_attention_pallas

                return flashmask_attention_pallas(q, k, v, idx, causal=causal)
            except Exception as exc:  # pragma: no cover - TPU-only path
                from paddle_tpu.kernels.select import warn_fallback

                warn_fallback("flashmask_attention", exc)
        bias = make_flashmask_bias(idx, q.shape[1], k.shape[1], causal)
        return _xla_attention(q, k, v, bias=bias, causal=causal)

    return call_op("flashmask_attention", _impl, query, key, value, startend_row_indices)


def make_flashmask_bias(startend_row_indices, sq: int, sk: int, causal: bool):
    """Densify FlashMask startend_row_indices into an additive bias
    [B, H_mask, Sq, Sk] (used by the XLA fallback and for parity tests against
    the Pallas kernel)."""
    idx = startend_row_indices  # [B, Hm, Sk, C]
    c = idx.shape[-1]
    rows = jnp.arange(sq)[:, None]  # [Sq, 1] query positions
    neg = jnp.asarray(-1e30, jnp.float32)

    def col_mask(bounds):  # bounds [B, Hm, Sk, C] → masked bool [B, Hm, Sq, Sk]
        if c == 1:
            start = bounds[..., 0]  # [B, Hm, Sk]
            masked = rows[None, None] >= start[:, :, None, :]
        elif c == 2:
            start = bounds[..., 0]
            end = bounds[..., 1]
            masked = (rows[None, None] >= start[:, :, None, :]) & (
                rows[None, None] < end[:, :, None, :]
            )
        elif c == 4:
            lts = bounds[..., 0]
            lte = bounds[..., 1]
            uts = bounds[..., 2]
            ute = bounds[..., 3]
            masked = (
                (rows[None, None] >= lts[:, :, None, :])
                & (rows[None, None] < lte[:, :, None, :])
            ) | (
                (rows[None, None] >= uts[:, :, None, :])
                & (rows[None, None] < ute[:, :, None, :])
            )
        else:
            raise ValueError(f"startend_row_indices last dim must be 1/2/4, got {c}")
        return masked

    masked = col_mask(idx)
    return jnp.where(masked, neg, 0.0)


class sdp_kernel:  # noqa: N801 - context-manager compat shim
    """Kernel-selection context (torch/paddle compat); on TPU the Pallas flag
    is the only switch."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        self._enable_flash = enable_flash

    def __enter__(self):
        from paddle_tpu.flags import set_flags

        self._prev = GLOBAL_FLAGS.get("use_pallas_attention")
        set_flags({"use_pallas_attention": self._enable_flash})
        return self

    def __exit__(self, *a):
        from paddle_tpu.flags import set_flags

        set_flags({"use_pallas_attention": self._prev})


def flash_attn_qkvpacked(
    qkv,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """Packed-QKV flash attention (reference ``flash_attn_qkvpacked``):
    ``qkv`` is ``[B, S, 3, H, D]`` (or ``[B, S, 3*H, D]``); unpacks and
    dispatches to :func:`flash_attention`."""
    if len(qkv.shape) == 4:  # [B, S, 3*H, D]
        h3 = qkv.shape[2]
        qkv = qkv.reshape([qkv.shape[0], qkv.shape[1], 3, h3 // 3, qkv.shape[3]])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    return flash_attention(
        q, k, v, dropout=dropout, causal=causal, return_softmax=return_softmax,
        training=training,
    )


def flash_attn_varlen_qkvpacked(
    qkv,
    cu_seqlens_q,
    cu_seqlens_k,
    max_seqlen_q,
    max_seqlen_k,
    scale=1.0,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """Packed-QKV varlen attention (reference ``flash_attn_varlen_qkvpacked``)
    over the unpadded [total_tokens, 3, H, D] layout."""
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    return flash_attn_unpadded(
        q, k, v, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
        scale=scale, dropout=dropout, causal=causal,
        return_softmax=return_softmax, training=training,
    )
