"""``paddle_tpu.nn.functional`` — functional nn API (reference
``python/paddle/nn/functional/``)."""

from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *  # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.ring_attention import ring_flash_attention  # noqa: F401
from paddle_tpu.nn.functional.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attn_qkvpacked,
    flash_attn_unpadded,
    flash_attn_varlen_qkvpacked,
    flashmask_attention,
    scaled_dot_product_attention,
    sdp_kernel,
)
from paddle_tpu.ops.search import where  # noqa: F401


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """Mask [*, maxlen] with 1 for positions < length (reference sequence_mask op)."""
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import call_op
    from paddle_tpu.core.dtypes import convert_dtype

    def _impl(l):  # noqa: E741
        m = int(maxlen) if maxlen is not None else int(l.max())
        return (jnp.arange(m) < l[..., None]).astype(convert_dtype(dtype))

    return call_op("sequence_mask", _impl, lengths)
