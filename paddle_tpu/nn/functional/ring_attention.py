"""Context-parallel (ring) attention — Tensor-level API.

Beyond-reference feature (SURVEY §5.7 TPU translation): the reference's 'sep'
axis leaves the attention exchange to model code; here ring attention is a
first-class op. See ``paddle_tpu.kernels.ring_attention`` for the ring
schedule itself.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = ["ring_flash_attention"]


def ring_flash_attention(
    query: Any,
    key: Any,
    value: Any,
    mesh: Any = None,
    axis_name: str = "sep",
    causal: bool = True,
    scale: Optional[float] = None,
    name: Optional[str] = None,
) -> Any:
    """Ring attention over ``[B, S, H, D]`` tensors with the sequence dim
    sharded over ``axis_name`` of ``mesh`` (defaults to the global mesh)."""
    from paddle_tpu.core.dispatch import call_op
    from paddle_tpu.distributed.mesh import get_mesh
    from paddle_tpu.kernels.ring_attention import ring_flash_attention as _ring

    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("ring_flash_attention needs a mesh (dist.init_mesh/set_mesh)")

    def _impl(q, k, v):
        return _ring(q, k, v, mesh, axis_name=axis_name, causal=causal, scale=scale)

    return call_op("ring_flash_attention", _impl, query, key, value)
