"""Gradient clipping (reference ``python/paddle/nn/clip.py``:
``ClipGradByGlobalNorm``/``ClipGradByNorm``/``ClipGradByValue``).

Under hybrid parallelism the global norm must be reduced across model-parallel
groups — ``HybridParallelClipGrad`` in ``paddle_tpu.distributed`` wraps these.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, Tensor]]) -> List[Tuple[Tensor, Tensor]]:
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max: float, min: Optional[float] = None) -> None:  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm: float) -> None:
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.data.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm: float = 1.0, group_name: str = "default_group", auto_skip_clip: bool = False) -> None:
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def global_norm(self, grads: List[Tensor]) -> Any:
        sq = [jnp.sum(jnp.square(g.data.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return jnp.zeros((), jnp.float32)
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def __call__(self, params_grads):
        # need_clip=False params are excluded from BOTH the norm sum and the
        # scaling (reference semantics: nn/clip.py ClipGradByGlobalNorm skips
        # params whose ParamAttr sets need_clip=False entirely).
        grads = [
            g for p, g in params_grads
            if g is not None and getattr(p, "need_clip", True)
        ]
        if not grads:
            return params_grads
        gnorm = self.global_norm(grads)
        factor = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor).astype(g.dtype))))
        return out


def clip_grad_norm_(parameters: Any, max_norm: float, norm_type: float = 2.0, error_if_nonfinite: bool = False) -> Tensor:
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.data)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.data.astype(jnp.float32)), norm_type)) for g in grads),
            1.0 / norm_type,
        )
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    import paddle_tpu

    with paddle_tpu.no_grad():
        for p in params:
            if p.grad is not None:
                p.grad.set_value(p.grad.data * factor)
    return Tensor(total)
