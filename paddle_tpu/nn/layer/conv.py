"""Conv layers (reference ``python/paddle/nn/layer/conv.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np

import paddle_tpu.nn.functional as F
from paddle_tpu.nn.layer.layers import Layer


def _ntuple(v: Any, n: int) -> tuple:
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels: int,
        out_channels: int,
        kernel_size: Any,
        ndim: int,
        stride: Any = 1,
        padding: Any = 0,
        dilation: Any = 1,
        groups: int = 1,
        padding_mode: str = "zeros",
        weight_attr: Any = None,
        bias_attr: Any = None,
        data_format: str = "NCHW",
        transpose: bool = False,
        output_padding: Any = 0,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._ndim = ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, ndim)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.output_padding = output_padding
        self._transpose = transpose
        if transpose:
            shape = [in_channels, out_channels // groups, *self.kernel_size]
        else:
            shape = [out_channels, in_channels // groups, *self.kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            shape,
            attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in, negative_slope=np.sqrt(5.0), nonlinearity="leaky_relu"),
        )
        if bias_attr is not False:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound),
            )
        else:
            self.bias = None

    def extra_repr(self) -> str:
        return (
            f"{self.in_channels}, {self.out_channels}, kernel_size={self.kernel_size}, "
            f"stride={self.stride}, padding={self.padding}"
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x: Any) -> Any:
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x: Any) -> Any:
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x: Any) -> Any:
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCL") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x: Any, output_size: Any = None) -> Any:
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x: Any, output_size: Any = None) -> Any:
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCDHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x: Any, output_size: Any = None) -> Any:
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, self.data_format)
