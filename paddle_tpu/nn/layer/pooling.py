"""Pooling layers (reference ``python/paddle/nn/layer/pooling.py``)."""

from __future__ import annotations

from typing import Any, Optional

import paddle_tpu.nn.functional as F
from paddle_tpu.nn.layer.layers import Layer


class _PoolNd(Layer):
    def __init__(self, kernel_size: Any, stride: Any = None, padding: Any = 0, ceil_mode: bool = False, data_format: Optional[str] = None, **kw: Any) -> None:
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def extra_repr(self) -> str:
        return f"kernel_size={self.kernel_size}, stride={self.stride}, padding={self.padding}"


class MaxPool1D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.data_format or "NCL")


class MaxPool2D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.data_format or "NCHW")


class MaxPool3D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.data_format or "NCDHW")


class AvgPool1D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, data_format=self.data_format or "NCL")


class AvgPool2D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, data_format=self.data_format or "NCHW")


class AvgPool3D(_PoolNd):
    def forward(self, x: Any) -> Any:
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, data_format=self.data_format or "NCDHW")


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size: Any, name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size

    def forward(self, x: Any) -> Any:
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size: Any, data_format: str = "NCHW", name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size: Any, data_format: str = "NCDHW", name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size: Any, return_mask: bool = False, name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size

    def forward(self, x: Any) -> Any:
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size: Any, return_mask: bool = False, name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size

    def forward(self, x: Any) -> Any:
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size: Any, return_mask: bool = False, name: Any = None) -> None:
        super().__init__()
        self.output_size = output_size

    def forward(self, x: Any) -> Any:
        return F.adaptive_max_pool3d(x, self.output_size)
