"""Transformer layers (reference ``python/paddle/nn/layer/transformer.py``).

MultiHeadAttention routes through the flash-attention functional API so the
Pallas kernel is picked up on TPU when applicable.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import paddle_tpu.nn.functional as F
from paddle_tpu.nn.layer.common import Dropout, Linear
from paddle_tpu.nn.layer.container import LayerList
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn.layer.norm import LayerNorm
from paddle_tpu.ops.manipulation import concat, reshape
from paddle_tpu.ops.linalg import transpose


class MultiHeadAttention(Layer):
    """Multi-head attention with optional cached decoding.

    Reference: ``python/paddle/nn/layer/transformer.py`` MultiHeadAttention.
    Layout [batch, seq, embed]. Cache holds (k, v) tensors.
    """

    class Cache:
        def __init__(self, k: Any, v: Any) -> None:
            self.k = k
            self.v = v

    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout: float = 0.0,
        kdim: Optional[int] = None,
        vdim: Optional[int] = None,
        need_weights: bool = False,
        weight_attr: Any = None,
        bias_attr: Any = None,
    ) -> None:
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x: Any, seq: int) -> Any:
        return reshape(x, [x.shape[0], seq, self.num_heads, self.head_dim])

    def forward(
        self,
        query: Any,
        key: Any = None,
        value: Any = None,
        attn_mask: Any = None,
        cache: Any = None,
    ) -> Any:
        key = query if key is None else key
        value = query if value is None else value
        q = self._shape(self.q_proj(query), query.shape[1])
        k = self._shape(self.k_proj(key), key.shape[1])
        v = self._shape(self.v_proj(value), value.shape[1])
        if cache is not None:
            k = concat([cache.k, k], axis=1)
            v = concat([cache.v, v], axis=1)
            cache = MultiHeadAttention.Cache(k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout if self.training else 0.0
        )
        out = reshape(out, [out.shape[0], out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out

    def gen_cache(self, key: Any, value: Any = None, type: Any = None) -> "MultiHeadAttention.Cache":  # noqa: A002
        from paddle_tpu.ops.creation import zeros

        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
        v = zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
        return MultiHeadAttention.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout: float = 0.1,
        activation: str = "relu",
        attn_dropout: Optional[float] = None,
        act_dropout: Optional[float] = None,
        normalize_before: bool = False,
        weight_attr: Any = None,
        bias_attr: Any = None,
        layer_norm_eps: float = 1e-5,
    ) -> None:
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, src: Any, src_mask: Any = None, cache: Any = None) -> Any:
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, src, src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer: TransformerEncoderLayer, num_layers: int, norm: Any = None) -> None:
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src: Any, src_mask: Any = None) -> Any:
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout: float = 0.1,
        activation: str = "relu",
        attn_dropout: Optional[float] = None,
        act_dropout: Optional[float] = None,
        normalize_before: bool = False,
        weight_attr: Any = None,
        bias_attr: Any = None,
        layer_norm_eps: float = 1e-5,
    ) -> None:
        super().__init__()
        self.normalize_before = normalize_before
        attn_drop = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_drop, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_drop, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt: Any, memory: Any, tgt_mask: Any = None, memory_mask: Any = None, cache: Any = None) -> Any:
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer: TransformerDecoderLayer, num_layers: int, norm: Any = None) -> None:
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt: Any, memory: Any, tgt_mask: Any = None, memory_mask: Any = None, cache: Any = None) -> Any:
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(
        self,
        d_model: int = 512,
        nhead: int = 8,
        num_encoder_layers: int = 6,
        num_decoder_layers: int = 6,
        dim_feedforward: int = 2048,
        dropout: float = 0.1,
        activation: str = "relu",
        attn_dropout: Optional[float] = None,
        act_dropout: Optional[float] = None,
        normalize_before: bool = False,
        weight_attr: Any = None,
        bias_attr: Any = None,
        custom_encoder: Any = None,
        custom_decoder: Any = None,
    ) -> None:
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr
            )
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr
            )
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, LayerNorm(d_model) if normalize_before else None)

    def forward(self, src: Any, tgt: Any, src_mask: Any = None, tgt_mask: Any = None, memory_mask: Any = None) -> Any:
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length: int) -> Any:
        from paddle_tpu.ops.creation import full, triu

        import paddle_tpu

        m = full([length, length], 0.0)
        mask = triu(full([length, length], float("-inf")), diagonal=1)
        return mask
