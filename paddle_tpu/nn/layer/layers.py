"""Layer: the module base class.

Reference: ``python/paddle/nn/layer/layers.py`` (2.7k lines) — parameter
registration via ``__setattr__``, sublayer tree, state_dict, train/eval,
forward hooks, ``to()`` casting. Parameters here are eager Tensors whose
buffers live on device (PJRT); a Layer is also directly traceable by
``paddle_tpu.jit`` because forward only touches Tensor ops.
"""

from __future__ import annotations

import collections
import contextlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.errors import InvalidArgumentError
from paddle_tpu.framework.param_attr import ParamAttr


@contextlib.contextmanager
def bind_param_arrays(named, param_arrays):
    """Temporarily point each ``(name, Parameter)`` in ``named`` at the
    corresponding raw jax array, restoring the originals on exit.

    This is THE way compiled inference paths thread live weights into a
    jitted function (``generation.py``'s three decode paths and the
    continuous-batching engine all use it): the params become trace inputs,
    so later weight updates are served by the same compiled program, and the
    restore runs even when tracing fails — no tracer ever leaks into the
    live Parameters."""
    saved = [p._data for _, p in named]
    for (_n, p), a in zip(named, param_arrays):
        p._data = a
    try:
        yield
    finally:
        for (_n, p), s in zip(named, saved):
            p._data = s


@contextlib.contextmanager
def bind_quant_scales(params, scales):
    """Temporarily point each quantized Parameter's ``_quant_scale`` at the
    corresponding raw jax array (usually a tracer), restoring the originals
    on exit — the scale-side companion of :func:`bind_param_arrays`. The
    engine threads weight-only int8 scales through its jitted step this way,
    so the scales are trace INPUTS (one compiled signature, donation-safe)
    rather than baked-in constants."""
    saved = [p._quant_scale for p in params]
    for p, s in zip(params, scales):
        p._quant_scale = s
    try:
        yield
    finally:
        for p, s in zip(params, saved):
            p._quant_scale = s


class HookRemoveHelper:
    def __init__(self, hooks: Dict[int, Callable], hook_id: int) -> None:
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self) -> None:
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: Any = "float32") -> None:
        self.training = True
        self._dtype = convert_dtype(dtype)
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names: set = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- registration ---------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            _remove_from(name, layers, buffers)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__() before assigning sublayers")
            _remove_from(name, params, buffers)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name] = Parameter(value._data, name=value.name)
            else:
                raise InvalidArgumentError(f"cannot assign {type(value)} to parameter {name}")
        elif layers is not None and name in layers:
            if value is None:
                layers[name] = None
            else:
                raise InvalidArgumentError(f"cannot assign {type(value)} to sublayer {name}")
        elif buffers is not None and name in buffers:
            if value is None:
                buffers[name] = None
            else:
                buffers[name] = value if isinstance(value, Tensor) else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str) -> Any:
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> Optional[Parameter]:
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True) -> None:
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    def create_parameter(
        self,
        shape: Sequence[int],
        attr: Any = None,
        dtype: Any = None,
        is_bias: bool = False,
        default_initializer: Any = None,
    ) -> Parameter:
        """Reference ``Layer.create_parameter``: ParamAttr + initializer →
        device Parameter."""
        from paddle_tpu.nn import initializer as I

        attr = ParamAttr._to_attr(attr)
        dtype = convert_dtype(dtype) if dtype is not None else self._dtype
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        p = Parameter(
            jnp.zeros(tuple(int(s) for s in shape), dtype),
            name=(attr.name if attr is not None else None),
            trainable=(attr.trainable if attr is not None else True),
        )
        init(p)
        if attr is not None:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.need_clip = attr.need_clip
        return p

    # -- traversal ------------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, layer in self.named_children():
            yield layer

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(
        self, prefix: str = "", include_self: bool = False, layers_set: Optional[set] = None
    ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None or id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(prefix=sub_prefix, layers_set=layers_set)

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        layers = (
            [(prefix, self)]
            + [
                (f"{prefix}.{n}" if prefix else n, l)
                for n, l in self.named_sublayers()
            ]
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        layers = (
            [(prefix, self)]
            + [(f"{prefix}.{n}" if prefix else n, l) for n, l in self.named_sublayers()]
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), b

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- modes ---------------------------------------------------------------
    def train(self) -> "Layer":
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self) -> "Layer":
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # -- state dict -----------------------------------------------------------
    def state_dict(
        self,
        destination: Optional[Dict[str, Tensor]] = None,
        include_sublayers: bool = True,
        structured_name_prefix: str = "",
        use_hook: bool = True,
    ) -> Dict[str, Tensor]:
        dest: Dict[str, Tensor] = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            # skip non-persistable buffers
            short = name.rsplit(".", 1)[-1]
            owner = self
            if short not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True) -> Tuple[List[str], List[str]]:
        """Load values into matching parameters/buffers; returns (missing, unexpected)."""
        own = self.state_dict()
        missing: List[str] = []
        unexpected: List[str] = [k for k in state_dict if k not in own]
        import paddle_tpu

        with paddle_tpu.no_grad():
            for name, target in own.items():
                if name not in state_dict:
                    missing.append(name)
                    continue
                value = state_dict[name]
                arr = value.numpy() if hasattr(value, "numpy") else np.asarray(value)
                target.set_value(arr)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device: Any = None, dtype: Any = None, blocking: Optional[bool] = None) -> "Layer":
        import paddle_tpu

        with paddle_tpu.no_grad():
            if dtype is not None:
                dt = convert_dtype(dtype)
                for p in self.parameters():
                    if jnp.issubdtype(jnp.dtype(p.dtype), jnp.floating):
                        p._data = p._data.astype(dt)
                for b in self.buffers():
                    if jnp.issubdtype(jnp.dtype(b.dtype), jnp.floating):
                        b._data = b._data.astype(dt)
                self._dtype = dt
            if device is not None:
                from paddle_tpu.core.device import _parse

                place = _parse(device) if isinstance(device, str) else device
                import jax as _jax

                for t in list(self.parameters()) + list(self.buffers()):
                    t._data = _jax.device_put(t._data, place.jax_device())
        return self

    def astype(self, dtype: Any) -> "Layer":
        return self.to(dtype=dtype)

    def float(self) -> "Layer":
        return self.to(dtype="float32")

    def bfloat16(self) -> "Layer":
        return self.to(dtype="bfloat16")

    # -- hooks + call ---------------------------------------------------------
    def register_forward_pre_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def forward(self, *inputs: Any, **kwargs: Any) -> Any:
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()"
        )

    def __call__(self, *inputs: Any, **kwargs: Any) -> Any:
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self) -> str:
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            mod_str = repr(layer)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self) -> None:
        for p in self.parameters():
            p.clear_grad()


def _remove_from(name: str, *dicts: Optional[Dict[str, Any]]) -> None:
    for d in dicts:
        if d is not None and name in d:
            del d[name]
