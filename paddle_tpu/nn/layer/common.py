"""Common layers: Linear, Embedding, Dropout, activations, etc.
(reference ``python/paddle/nn/layer/common.py`` + ``activation.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu.nn.layer.layers import Layer


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (paddle layout)."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr
        )
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        # weight-only int8 (engine-applied, FLAGS_weight_only_int8): the
        # Parameter carries its per-output-channel scales; the defop below
        # unwraps Tensor args, so the dispatch decision must happen HERE,
        # where the Parameter (and its _quant_scale) is still visible
        scale = getattr(self.weight, "_quant_scale", None)
        if scale is not None:
            return F.weight_only_linear(x, self.weight, scale, self.bias)
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self) -> str:
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        padding_idx: Optional[int] = None,
        sparse: bool = False,
        weight_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
        )

    def forward(self, x: Any) -> Any:
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self) -> str:
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p: float = 0.5, axis: Any = None, mode: str = "upscale_in_train", name: Any = None) -> None:
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x: Any) -> Any:
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self) -> str:
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCHW", name: Any = None) -> None:
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCDHW", name: Any = None) -> None:
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5, name: Any = None) -> None:
        super().__init__()
        self.p = p

    def forward(self, x: Any) -> Any:
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1) -> None:
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x: Any) -> Any:
        from paddle_tpu.ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__()

    def forward(self, x: Any) -> Any:
        return x


class Upsample(Layer):
    def __init__(
        self,
        size: Any = None,
        scale_factor: Any = None,
        mode: str = "nearest",
        align_corners: bool = False,
        data_format: str = "NCHW",
        name: Any = None,
    ) -> None:
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.interpolate(
            x,
            size=self.size,
            scale_factor=self.scale_factor,
            mode=self.mode,
            align_corners=self.align_corners,
            data_format=self.data_format,
        )


class Pad2D(Layer):
    def __init__(self, padding: Any, mode: str = "constant", value: float = 0.0, data_format: str = "NCHW", name: Any = None) -> None:
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        from paddle_tpu.ops.manipulation import pad

        return pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis: int = 1, eps: float = 1e-8) -> None:
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1: Any, x2: Any) -> Any:
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features: int, in2_features: int, out_features: int, weight_attr: Any = None, bias_attr: Any = None, name: Any = None) -> None:
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True) if bias_attr is not False else None

    def forward(self, x1: Any, x2: Any) -> Any:
        return F.bilinear(x1, x2, self.weight, self.bias)


# -- activation layers --------------------------------------------------------
def _act_layer(name: str, fn_name: str, **defaults: Any) -> type:
    def __init__(self, *args: Any, **kwargs: Any) -> None:  # noqa: N807
        Layer.__init__(self)
        merged = dict(defaults)
        merged.update(kwargs)
        self._kwargs = merged
        self._args = args

    def forward(self, x: Any) -> Any:
        return getattr(F, fn_name)(x, *self._args, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
GELU = _act_layer("GELU", "gelu")
Silu = _act_layer("Silu", "silu")
Swish = _act_layer("Swish", "swish")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
Tanh = _act_layer("Tanh", "tanh")
Softmax = _act_layer("Softmax", "softmax")
LogSoftmax = _act_layer("LogSoftmax", "log_softmax")
Softplus = _act_layer("Softplus", "softplus")
Softsign = _act_layer("Softsign", "softsign")
Softshrink = _act_layer("Softshrink", "softshrink")
Hardshrink = _act_layer("Hardshrink", "hardshrink")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardtanh = _act_layer("Hardtanh", "hardtanh")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu")
ELU = _act_layer("ELU", "elu")
CELU = _act_layer("CELU", "celu")
SELU = _act_layer("SELU", "selu")
Mish = _act_layer("Mish", "mish")
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
Maxout = _act_layer("Maxout", "maxout", groups=2)
GLU = _act_layer("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters: int = 1, init: float = 0.25, weight_attr: Any = None, data_format: str = "NCHW", name: Any = None) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x: Any) -> Any:
        return F.prelu(x, self.weight, data_format=self.data_format)
