"""Loss layers (reference ``python/paddle/nn/layer/loss.py``)."""

from __future__ import annotations

from typing import Any, Optional

import paddle_tpu.nn.functional as F
from paddle_tpu.nn.layer.layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(
        self,
        weight: Any = None,
        ignore_index: int = -100,
        reduction: str = "mean",
        soft_label: bool = False,
        axis: int = -1,
        use_softmax: bool = True,
        label_smoothing: float = 0.0,
        name: Any = None,
    ) -> None:
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.cross_entropy(
            input,
            label,
            weight=self.weight,
            ignore_index=self.ignore_index,
            reduction=self.reduction,
            soft_label=self.soft_label,
            axis=self.axis,
            use_softmax=self.use_softmax,
            label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean") -> None:
        super().__init__()
        self.reduction = reduction

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.mse_loss(input, label, reduction=self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean", name: Any = None) -> None:
        super().__init__()
        self.reduction = reduction

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.l1_loss(input, label, reduction=self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0, name: Any = None) -> None:
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.smooth_l1_loss(input, label, reduction=self.reduction, delta=self.delta)


class NLLLoss(Layer):
    def __init__(self, weight: Any = None, ignore_index: int = -100, reduction: str = "mean", name: Any = None) -> None:
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.nll_loss(input, label, weight=self.weight, ignore_index=self.ignore_index, reduction=self.reduction)


class BCELoss(Layer):
    def __init__(self, weight: Any = None, reduction: str = "mean", name: Any = None) -> None:
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.binary_cross_entropy(input, label, weight=self.weight, reduction=self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight: Any = None, reduction: str = "mean", pos_weight: Any = None, name: Any = None) -> None:
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit: Any, label: Any) -> Any:
        return F.binary_cross_entropy_with_logits(
            logit, label, weight=self.weight, reduction=self.reduction, pos_weight=self.pos_weight
        )


class KLDivLoss(Layer):
    def __init__(self, reduction: str = "mean", log_target: bool = False) -> None:
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        return F.kl_div(input, label, reduction=self.reduction, log_target=self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin: float = 0.0, reduction: str = "mean", name: Any = None) -> None:
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input: Any, other: Any, label: Any) -> Any:  # noqa: A002
        return F.margin_ranking_loss(input, other, label, margin=self.margin, reduction=self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank: int = 0, reduction: str = "mean") -> None:
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs: Any, labels: Any, input_lengths: Any, label_lengths: Any, norm_by_times: bool = False) -> Any:
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=self.blank, reduction=self.reduction)
