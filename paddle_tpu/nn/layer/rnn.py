"""Recurrent layers: SimpleRNN / LSTM / GRU cells and sequence wrappers.

TPU-native counterpart of the reference RNN stack
(``python/paddle/nn/layer/rnn.py:590`` ``RNNCellBase``, ``:741``
``SimpleRNNCell``, ``:918`` ``LSTMCell``, ``:1144`` ``GRUCell``, ``:1339``
``RNN``, ``:1514`` ``RNNBase`` → ``SimpleRNN``/``LSTM``/``GRU``).

Design: the recurrence is ONE dispatched op built on ``lax.scan`` — the whole
sequence compiles to a single fused XLA while-loop with the weights hoisted
out of the loop (the reference reaches the same shape only through the cuDNN
fused kernel; its fallback is a Python per-step loop). Variable-length
sequences use carry-select masking inside the scan, so shapes stay static and
the loop still tiles onto the MXU. Parameter names/shapes match the reference
(``weight_ih``: ``(k*hidden, input)`` etc.) for state_dict parity.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu.core.dispatch import call_op
from paddle_tpu.nn.layer.container import LayerList
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "RNNCellBase",
    "SimpleRNNCell",
    "LSTMCell",
    "GRUCell",
    "RNN",
    "BiRNN",
    "SimpleRNN",
    "LSTM",
    "GRU",
]


def _uniform_attr(hidden_size: int) -> Any:
    from paddle_tpu.nn import initializer as I

    std = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-std, std)


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (reference ``rnn.py:590``)."""

    def get_initial_states(
        self,
        batch_ref: Any,
        shape: Any = None,
        dtype: Any = None,
        init_value: float = 0.0,
        batch_dim_idx: int = 0,
    ) -> Any:
        from paddle_tpu.core.tensor import Tensor

        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        dtype = dtype or "float32"

        def build(s: Any) -> Tensor:
            dims = [batch] + [int(d) for d in s]
            return Tensor(jnp.full(dims, init_value, dtype=dtype))

        if isinstance(shape, (list, tuple)) and shape and isinstance(shape[0], (list, tuple)):
            return tuple(build(s) for s in shape)
        return build(shape)

    # Pure single-step over jax arrays; subclasses implement.
    @staticmethod
    def _step(x: Any, states: Any, params: Sequence[Any]) -> Tuple[Any, Any]:
        raise NotImplementedError

    def _params(self) -> List[Any]:
        raise NotImplementedError

    def forward(self, inputs: Any, states: Any = None) -> Tuple[Any, Any]:
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        step = type(self)._step

        def fn(x: Any, st: Any, *ps: Any) -> Tuple[Any, Any]:
            return step(x, st, ps)

        out, new_states = call_op(self.__class__.__name__, fn, inputs, states, *self._params())
        return out, new_states


class SimpleRNNCell(RNNCellBase):
    """Elman cell: ``h = act(x W_ih^T + b_ih + h W_hh^T + b_hh)``
    (reference ``rnn.py:741``)."""

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        activation: str = "tanh",
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError(f"activation must be tanh or relu, got {activation}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = (
            None
            if bias_ih_attr is False
            else self.create_parameter([hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init)
        )
        self.bias_hh = (
            None
            if bias_hh_attr is False
            else self.create_parameter([hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init)
        )
        self._act_relu = activation == "relu"

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.hidden_size,)

    def _params(self) -> List[Any]:
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps.append(self.bias_ih)
        if self.bias_hh is not None:
            ps.append(self.bias_hh)
        return ps

    def forward(self, inputs: Any, states: Any = None) -> Tuple[Any, Any]:
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        relu = self._act_relu
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None

        def fn(x: Any, h: Any, *ps: Any) -> Tuple[Any, Any]:
            h2 = _simple_rnn_step(x, h, ps, relu, has_bi, has_bh)
            return h2, h2

        out, new_h = call_op("simple_rnn_cell", fn, inputs, states, *self._params())
        return out, new_h


def _simple_rnn_step(
    x: Any, h: Any, ps: Sequence[Any], relu: bool, has_bi: bool, has_bh: bool
) -> Any:
    i = 2
    w_ih, w_hh = ps[0], ps[1]
    pre = x @ w_ih.T + h @ w_hh.T
    if has_bi:
        pre = pre + ps[i]
        i += 1
    if has_bh:
        pre = pre + ps[i]
    return jax.nn.relu(pre) if relu else jnp.tanh(pre)


class LSTMCell(RNNCellBase):
    """LSTM cell, paddle gate order ``i, f, g, o`` (reference ``rnn.py:918``).

    ``weight_ih``: ``(4H, I)``, ``weight_hh``: ``(4H, H or proj)``; optional
    ``weight_ho``: ``(H, proj)`` projects the hidden state.
    """

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        proj_size: int = 0,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        if proj_size and proj_size >= hidden_size:
            raise ValueError("proj_size must be smaller than hidden_size")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.proj_size = proj_size
        init = _uniform_attr(hidden_size)
        h_in = proj_size or hidden_size
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, h_in], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = (
            None
            if bias_ih_attr is False
            else self.create_parameter([4 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init)
        )
        self.bias_hh = (
            None
            if bias_hh_attr is False
            else self.create_parameter([4 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init)
        )
        self.weight_ho = (
            self.create_parameter([hidden_size, proj_size], default_initializer=init)
            if proj_size
            else None
        )

    @property
    def state_shape(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        return ((self.proj_size or self.hidden_size,), (self.hidden_size,))

    def _params(self) -> List[Any]:
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps.append(self.bias_ih)
        if self.bias_hh is not None:
            ps.append(self.bias_hh)
        if self.weight_ho is not None:
            ps.append(self.weight_ho)
        return ps

    def forward(self, inputs: Any, states: Any = None) -> Tuple[Any, Any]:
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None
        has_proj = self.weight_ho is not None

        def fn(x: Any, st: Any, *ps: Any) -> Tuple[Any, Any]:
            h2, c2 = _lstm_step(x, tuple(st), ps, has_bi, has_bh, has_proj)
            return h2, (h2, c2)

        out, new_states = call_op("lstm_cell", fn, inputs, tuple(states), *self._params())
        return out, new_states


def _lstm_step(
    x: Any,
    states: Tuple[Any, Any],
    ps: Sequence[Any],
    has_bi: bool,
    has_bh: bool,
    has_proj: bool,
) -> Tuple[Any, Any]:
    h, c = states
    i = 2
    gates = x @ ps[0].T + h @ ps[1].T
    if has_bi:
        gates = gates + ps[i]
        i += 1
    if has_bh:
        gates = gates + ps[i]
        i += 1
    gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
    c2 = jax.nn.sigmoid(gf) * c + jax.nn.sigmoid(gi) * jnp.tanh(gg)
    h2 = jax.nn.sigmoid(go) * jnp.tanh(c2)
    if has_proj:
        h2 = h2 @ ps[i]
    return h2, c2


class GRUCell(RNNCellBase):
    """GRU cell, paddle gate order ``r, z, c`` with
    ``h = z*h_prev + (1-z)*c~`` (reference ``rnn.py:1144``)."""

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init
        )
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init
        )
        self.bias_ih = (
            None
            if bias_ih_attr is False
            else self.create_parameter([3 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init)
        )
        self.bias_hh = (
            None
            if bias_hh_attr is False
            else self.create_parameter([3 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init)
        )

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.hidden_size,)

    def _params(self) -> List[Any]:
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps.append(self.bias_ih)
        if self.bias_hh is not None:
            ps.append(self.bias_hh)
        return ps

    def forward(self, inputs: Any, states: Any = None) -> Tuple[Any, Any]:
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None

        def fn(x: Any, h: Any, *ps: Any) -> Tuple[Any, Any]:
            h2 = _gru_step(x, h, ps, has_bi, has_bh)
            return h2, h2

        out, new_h = call_op("gru_cell", fn, inputs, states, *self._params())
        return out, new_h


def _gru_step(x: Any, h: Any, ps: Sequence[Any], has_bi: bool, has_bh: bool) -> Any:
    i = 2
    xg = x @ ps[0].T
    hg = h @ ps[1].T
    if has_bi:
        xg = xg + ps[i]
        i += 1
    if has_bh:
        hg = hg + ps[i]
    xr, xz, xc = jnp.split(xg, 3, axis=-1)
    hr, hz, hc = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


def _cell_scan_fn(cell: "RNNCellBase") -> Tuple[Any, List[Any]]:
    """Return (pure step over (x, states, params), param tensors to pass as
    op inputs) for ``cell``."""
    if isinstance(cell, SimpleRNNCell):
        relu, bi, bh = cell._act_relu, cell.bias_ih is not None, cell.bias_hh is not None

        def step(x: Any, st: Any, ps: Sequence[Any]) -> Tuple[Any, Any]:
            h2 = _simple_rnn_step(x, st, ps, relu, bi, bh)
            return h2, h2

    elif isinstance(cell, LSTMCell):
        bi, bh = cell.bias_ih is not None, cell.bias_hh is not None
        proj = cell.weight_ho is not None

        def step(x: Any, st: Any, ps: Sequence[Any]) -> Tuple[Any, Any]:
            h2, c2 = _lstm_step(x, tuple(st), ps, bi, bh, proj)
            return h2, (h2, c2)

    elif isinstance(cell, GRUCell):
        bi, bh = cell.bias_ih is not None, cell.bias_hh is not None

        def step(x: Any, st: Any, ps: Sequence[Any]) -> Tuple[Any, Any]:
            h2 = _gru_step(x, st, ps, bi, bh)
            return h2, h2

    elif type(cell)._step is not RNNCellBase._step:
        # Custom cell implementing the pure-step protocol.
        cell_step = type(cell)._step

        def step(x: Any, st: Any, ps: Sequence[Any]) -> Tuple[Any, Any]:
            return cell_step(x, st, ps)

    else:
        # Generic cell (the reference's documented extension pattern: override
        # forward()). Run its eager forward under tracing, functional-call
        # style: the cell's parameters are real op inputs, substituted into
        # the layer for the duration of the step, so they receive gradients —
        # as closed-over constants they would be silently non-differentiable.
        gen_params = list(cell.parameters())

        def step(x: Any, st: Any, ps: Sequence[Any]) -> Tuple[Any, Any]:
            from paddle_tpu.core.tensor import Tensor

            def wrap(v: Any) -> Any:
                return v if isinstance(v, Tensor) else Tensor(v)

            def unwrap(v: Any) -> Any:
                return v.data if isinstance(v, Tensor) else v

            is_t = lambda v: isinstance(v, Tensor)  # noqa: E731
            saved = [(p, p._data) for p in gen_params]
            try:
                for p, arr in zip(gen_params, ps):
                    p._data = arr
                out, new_st = cell(wrap(x), jax.tree_util.tree_map(wrap, st))
            finally:
                for p, d in saved:
                    p._data = d
            return (
                jax.tree_util.tree_map(unwrap, out, is_leaf=is_t),
                jax.tree_util.tree_map(unwrap, new_st, is_leaf=is_t),
            )

        return step, gen_params
    return step, cell._params()


class RNN(Layer):
    """Run a cell over a sequence as one ``lax.scan`` op
    (reference ``rnn.py:1339``)."""

    def __init__(self, cell: RNNCellBase, is_reverse: bool = False, time_major: bool = False) -> None:
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(
        self,
        inputs: Any,
        initial_states: Any = None,
        sequence_length: Any = None,
        **kwargs: Any,
    ) -> Tuple[Any, Any]:
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, self.cell.state_shape, batch_dim_idx=batch_idx
            )
        step, params = _cell_scan_fn(self.cell)
        time_major = self.time_major
        reverse = self.is_reverse
        has_len = sequence_length is not None

        def fn(xs: Any, init: Any, *rest: Any) -> Tuple[Any, Any]:
            if has_len:
                seq_len, ps = rest[0], rest[1:]
            else:
                seq_len, ps = None, rest
            if not time_major:
                xs = jnp.swapaxes(xs, 0, 1)  # [B,T,...] -> [T,B,...]
            t_steps = xs.shape[0]
            t_index = jnp.arange(t_steps)

            def body(carry: Any, xt: Any) -> Tuple[Any, Any]:
                if seq_len is None:
                    out, new_states = step(xt, carry, ps)
                    return new_states, out
                x_t, t = xt
                out, new_states = step(x_t, carry, ps)
                mask = (t < seq_len)  # [B] bool
                sel = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(
                        mask.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
                    ),
                    new_states,
                    carry,
                )
                # Zero outputs at padded steps (torch pack_padded semantics —
                # intentional deviation from the reference, which keeps raw
                # step outputs past seq_len). Tree-mapped: custom cells may
                # emit nested outputs.
                out_masked = jax.tree_util.tree_map(
                    lambda o: o
                    * mask.reshape((-1,) + (1,) * (o.ndim - 1)).astype(o.dtype),
                    out,
                )
                return sel, out_masked

            xs_in = (xs, t_index) if seq_len is not None else xs
            final, outs = jax.lax.scan(body, init, xs_in, reverse=reverse)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return outs, final

        args = [inputs, initial_states]
        if has_len:
            args.append(sequence_length)
        args.extend(params)
        outputs, final_states = call_op("rnn_scan", fn, *args)
        return outputs, final_states


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference ``rnn.py``),
    concatenating fw/bw outputs on the feature axis."""

    def __init__(self, cell_fw: RNNCellBase, cell_bw: RNNCellBase, time_major: bool = False) -> None:
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(
        self,
        inputs: Any,
        initial_states: Any = None,
        sequence_length: Any = None,
        **kwargs: Any,
    ) -> Tuple[Any, Any]:
        states_fw, states_bw = (None, None) if initial_states is None else initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        import paddle_tpu as ops

        outputs = ops.concat([out_fw, out_bw], axis=-1)
        return outputs, (st_fw, st_bw)


class RNNBase(LayerList):
    """Multi-layer / bidirectional driver (reference ``rnn.py:1514``)."""

    def __init__(
        self,
        mode: str,
        input_size: int,
        hidden_size: int,
        num_layers: int = 1,
        direction: str = "forward",
        time_major: bool = False,
        dropout: float = 0.0,
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        proj_size: int = 0,
        activation: str = "tanh",
    ) -> None:
        super().__init__()
        bidirect = direction in ("bidirectional", "bidirect")
        if not bidirect and direction != "forward":
            raise ValueError(f"direction should be forward or bidirect, got {direction}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if bidirect else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.proj_size = proj_size
        self.state_components = 2 if mode == "LSTM" else 1

        kwargs = {
            "weight_ih_attr": weight_ih_attr,
            "weight_hh_attr": weight_hh_attr,
            "bias_ih_attr": bias_ih_attr,
            "bias_hh_attr": bias_hh_attr,
        }
        if mode == "LSTM":
            cell_cls = LSTMCell
            kwargs["proj_size"] = proj_size
        elif mode == "GRU":
            cell_cls = GRUCell
        else:
            cell_cls = SimpleRNNCell
            kwargs["activation"] = "relu" if mode == "RNN_RELU" else activation

        in_size = proj_size or hidden_size
        if not bidirect:
            self.append(RNN(cell_cls(input_size, hidden_size, **kwargs), False, time_major))
            for _ in range(1, num_layers):
                self.append(RNN(cell_cls(in_size, hidden_size, **kwargs), False, time_major))
        else:
            self.append(
                BiRNN(
                    cell_cls(input_size, hidden_size, **kwargs),
                    cell_cls(input_size, hidden_size, **kwargs),
                    time_major,
                )
            )
            for _ in range(1, num_layers):
                self.append(
                    BiRNN(
                        cell_cls(2 * in_size, hidden_size, **kwargs),
                        cell_cls(2 * in_size, hidden_size, **kwargs),
                        time_major,
                    )
                )

    def _split_states(self, states: Any) -> List[Any]:
        """[L*D, B, H]-stacked states → per-(layer,direction) list."""
        import paddle_tpu as ops

        if self.state_components == 1:
            comps = [states]
        else:
            comps = list(states)
        per_ld = [
            [ops.squeeze(s, axis=0) for s in ops.split(c, self.num_layers * self.num_directions, axis=0)]
            for c in comps
        ]
        out: List[Any] = []
        for i in range(self.num_layers):
            layer_states = []
            for d in range(self.num_directions):
                idx = i * self.num_directions + d
                if self.state_components == 1:
                    layer_states.append(per_ld[0][idx])
                else:
                    layer_states.append(tuple(c[idx] for c in per_ld))
            out.append(layer_states[0] if self.num_directions == 1 else tuple(layer_states))
        return out

    def _concat_states(self, states_list: List[Any]) -> Any:
        import paddle_tpu as ops

        flat: List[List[Any]] = [[] for _ in range(self.state_components)]
        for layer_states in states_list:
            dirs = [layer_states] if self.num_directions == 1 else list(layer_states)
            for st in dirs:
                comps = [st] if self.state_components == 1 else list(st)
                for k, c in enumerate(comps):
                    flat[k].append(c)
        stacked = [ops.stack(c, axis=0) for c in flat]
        return stacked[0] if self.state_components == 1 else tuple(stacked)

    def forward(
        self, inputs: Any, initial_states: Any = None, sequence_length: Any = None
    ) -> Tuple[Any, Any]:
        states_list = (
            self._split_states(initial_states)
            if initial_states is not None
            else [None] * self.num_layers
        )
        out = inputs
        final: List[Any] = []
        for i, layer in enumerate(self):
            out, st = layer(out, states_list[i], sequence_length)
            final.append(st)
            if self.dropout > 0.0 and i < self.num_layers - 1:
                out = F.dropout(out, p=self.dropout, training=self.training)
        return out, self._concat_states(final)


class SimpleRNN(RNNBase):
    """Multi-layer Elman RNN (reference ``rnn.py:1859``)."""

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        num_layers: int = 1,
        direction: str = "forward",
        time_major: bool = False,
        dropout: float = 0.0,
        activation: str = "tanh",
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(
            mode,
            input_size,
            hidden_size,
            num_layers,
            direction,
            time_major,
            dropout,
            weight_ih_attr,
            weight_hh_attr,
            bias_ih_attr,
            bias_hh_attr,
            activation=activation,
        )


class LSTM(RNNBase):
    """Multi-layer LSTM (reference ``rnn.py:1982``)."""

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        num_layers: int = 1,
        direction: str = "forward",
        time_major: bool = False,
        dropout: float = 0.0,
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        proj_size: int = 0,
        name: Optional[str] = None,
    ) -> None:
        super().__init__(
            "LSTM",
            input_size,
            hidden_size,
            num_layers,
            direction,
            time_major,
            dropout,
            weight_ih_attr,
            weight_hh_attr,
            bias_ih_attr,
            bias_hh_attr,
            proj_size,
        )


class GRU(RNNBase):
    """Multi-layer GRU (reference ``rnn.py:2119``)."""

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        num_layers: int = 1,
        direction: str = "forward",
        time_major: bool = False,
        dropout: float = 0.0,
        weight_ih_attr: Any = None,
        weight_hh_attr: Any = None,
        bias_ih_attr: Any = None,
        bias_hh_attr: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__(
            "GRU",
            input_size,
            hidden_size,
            num_layers,
            direction,
            time_major,
            dropout,
            weight_ih_attr,
            weight_hh_attr,
            bias_ih_attr,
            bias_hh_attr,
        )
