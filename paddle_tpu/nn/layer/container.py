"""Container layers (reference ``python/paddle/nn/layer/container.py``)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple, Union

from paddle_tpu.core.tensor import Parameter
from paddle_tpu.nn.layer.layers import Layer


class Sequential(Layer):
    def __init__(self, *layers: Any) -> None:
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx: Union[int, slice]) -> Any:
        items = list(self._sub_layers.values())
        if isinstance(idx, slice):
            return Sequential(*items[idx])
        return items[idx]

    def __len__(self) -> int:
        return len(self._sub_layers)

    def __iter__(self) -> Iterator[Layer]:
        return iter(self._sub_layers.values())

    def forward(self, x: Any) -> Any:
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers: Optional[Iterable[Layer]] = None) -> None:
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx: Union[int, slice]) -> Any:
        items = list(self._sub_layers.values())
        if isinstance(idx, slice):
            return LayerList(items[idx])
        return items[idx]

    def __setitem__(self, idx: int, layer: Layer) -> None:
        self._sub_layers[str(idx % len(self))] = layer

    def __len__(self) -> int:
        return len(self._sub_layers)

    def __iter__(self) -> Iterator[Layer]:
        return iter(self._sub_layers.values())

    def append(self, layer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index: int, layer: Layer) -> None:
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers: Iterable[Layer]) -> "LayerList":
        for layer in sublayers:
            self.append(layer)
        return self


class ParameterList(Layer):
    def __init__(self, parameters: Optional[Iterable[Parameter]] = None) -> None:
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx: int) -> Parameter:
        return list(self._parameters.values())[idx]

    def __len__(self) -> int:
        return len(self._parameters)

    def __iter__(self) -> Iterator[Parameter]:
        return iter(self._parameters.values())

    def append(self, parameter: Parameter) -> "ParameterList":
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers: Optional[Dict[str, Layer]] = None) -> None:
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key: str) -> Layer:
        return self._sub_layers[key]

    def __setitem__(self, key: str, layer: Layer) -> None:
        self.add_sublayer(key, layer)

    def __delitem__(self, key: str) -> None:
        del self._sub_layers[key]

    def __len__(self) -> int:
        return len(self._sub_layers)

    def __iter__(self) -> Iterator[str]:
        return iter(self._sub_layers)

    def __contains__(self, key: str) -> bool:
        return key in self._sub_layers

    def clear(self) -> None:
        self._sub_layers.clear()

    def pop(self, key: str) -> Layer:
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def keys(self) -> Iterable[str]:
        return self._sub_layers.keys()

    def items(self) -> Iterable[Tuple[str, Layer]]:
        return self._sub_layers.items()

    def values(self) -> Iterable[Layer]:
        return self._sub_layers.values()

    def update(self, sublayers: Dict[str, Layer]) -> None:
        for k, v in sublayers.items():
            self.add_sublayer(k, v)
