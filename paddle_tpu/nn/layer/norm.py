"""Normalization layers (reference ``python/paddle/nn/layer/norm.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer


class _BatchNormBase(Layer):
    def __init__(
        self,
        num_features: int,
        momentum: float = 0.9,
        epsilon: float = 1e-5,
        weight_attr: Any = None,
        bias_attr: Any = None,
        data_format: str = "NCHW",
        use_global_stats: Optional[bool] = None,
        name: Any = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x: Any) -> Any:
        training = self.training and not (self.use_global_stats or False)
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            weight=self.weight,
            bias=self.bias,
            training=training,
            momentum=self.momentum,
            epsilon=self.epsilon,
            data_format=self.data_format,
        )

    def extra_repr(self) -> str:
        return f"num_features={self.num_features}, momentum={self.momentum}, epsilon={self.epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under SPMD jit, XLA computes global batch
    stats automatically when the batch axis is sharded (GSPMD all-reduces the
    partial moments) — so this is the same computation as BatchNorm; the
    distinction the reference draws (``nn.SyncBatchNorm`` over NCCL) is
    compiler-handled on TPU."""

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer) -> Layer:
        return layer


class LayerNorm(Layer):
    def __init__(
        self,
        normalized_shape: Any,
        epsilon: float = 1e-5,
        weight_attr: Any = None,
        bias_attr: Any = None,
        name: Any = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self) -> str:
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """RMSNorm layer (reference exposes fused rms_norm via
    ``paddle.incubate.nn.functional.fused_rms_norm``; first-class layer here)."""

    def __init__(
        self,
        normalized_shape: Any,
        epsilon: float = 1e-6,
        weight_attr: Any = None,
        name: Any = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None

    def forward(self, x: Any) -> Any:
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(
        self,
        num_groups: int,
        num_channels: int,
        epsilon: float = 1e-5,
        weight_attr: Any = None,
        bias_attr: Any = None,
        data_format: str = "NCHW",
        name: Any = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(
        self,
        num_features: int,
        epsilon: float = 1e-5,
        momentum: float = 0.9,
        weight_attr: Any = None,
        bias_attr: Any = None,
        data_format: str = "NCHW",
        name: Any = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x: Any) -> Any:
        return F.instance_norm(x, self.weight, self.bias, self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size: int, alpha: float = 1e-4, beta: float = 0.75, k: float = 1.0, data_format: str = "NCHW", name: Any = None) -> None:
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x: Any) -> Any:
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape: Sequence[int], dim: int = 0, power_iters: int = 1, epsilon: float = 1e-12, name: Any = None) -> None:
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from paddle_tpu.nn import initializer as I

        self.weight_u = self.create_parameter([h], default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter([w], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight: Any) -> Any:
        import paddle_tpu

        mat = weight
        if self.dim != 0:
            perm = [self.dim] + [d for d in range(mat.ndim) if d != self.dim]
            from paddle_tpu.ops.linalg import transpose

            mat = transpose(mat, perm)
        h = mat.shape[0]
        mat2d = mat.reshape([h, -1])
        u, v = self.weight_u, self.weight_v
        with paddle_tpu.no_grad():
            for _ in range(self.power_iters):
                v_new = (mat2d.T @ u)
                v_new = v_new / (v_new.norm() + self.epsilon)
                u_new = mat2d @ v_new
                u_new = u_new / (u_new.norm() + self.epsilon)
                u.set_value(u_new.data)
                v.set_value(v_new.data)
        sigma = (u.reshape([1, -1]) @ mat2d @ v.reshape([-1, 1])).reshape([])
        return weight / sigma
