"""Parameter initializers (reference ``python/paddle/nn/initializer/``).

Each initializer is a callable applied to a Parameter in-place (set_value),
drawing from the global splittable PRNG — deterministic under ``paddle_tpu.seed``.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.core.rng as _rng
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Dirac",
    "Orthogonal",
    "calculate_gain",
]


def calculate_gain(nonlinearity: str, param: Optional[float] = None) -> float:
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fans(shape: Sequence[int]) -> tuple:
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param: Tensor, block: Any = None) -> None:
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0) -> None:
        self.value = value

    def __call__(self, param: Tensor, block: Any = None) -> None:
        param.set_value(jnp.full(tuple(param.shape), self.value, param.dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0) -> None:
        self.mean, self.std = mean, std

    def __call__(self, param: Tensor, block: Any = None) -> None:
        sample = self.mean + self.std * jax.random.normal(
            _rng.next_key(), tuple(param.shape), jnp.float32
        )
        param.set_value(sample.astype(param.dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0) -> None:
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param: Tensor, block: Any = None) -> None:
        sample = jax.random.truncated_normal(
            _rng.next_key(), self.a, self.b, tuple(param.shape), jnp.float32
        )
        param.set_value((self.mean + self.std * sample).astype(param.dtype))


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0) -> None:
        self.low, self.high = low, high

    def __call__(self, param: Tensor, block: Any = None) -> None:
        sample = jax.random.uniform(
            _rng.next_key(), tuple(param.shape), jnp.float32, self.low, self.high
        )
        param.set_value(sample.astype(param.dtype))


class XavierNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0) -> None:
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def __call__(self, param: Tensor, block: Any = None) -> None:
        fi, fo = _fans(param.shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self._gain * math.sqrt(2.0 / (fi + fo))
        sample = std * jax.random.normal(_rng.next_key(), tuple(param.shape), jnp.float32)
        param.set_value(sample.astype(param.dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0) -> None:
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def __call__(self, param: Tensor, block: Any = None) -> None:
        fi, fo = _fans(param.shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self._gain * math.sqrt(6.0 / (fi + fo))
        sample = jax.random.uniform(
            _rng.next_key(), tuple(param.shape), jnp.float32, -limit, limit
        )
        param.set_value(sample.astype(param.dtype))


class KaimingNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu") -> None:
        self._fan_in = fan_in
        self._negative_slope = negative_slope
        self._nonlinearity = nonlinearity

    def __call__(self, param: Tensor, block: Any = None) -> None:
        fi, _ = _fans(param.shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self._nonlinearity, self._negative_slope)
        std = gain / math.sqrt(fi)
        sample = std * jax.random.normal(_rng.next_key(), tuple(param.shape), jnp.float32)
        param.set_value(sample.astype(param.dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu") -> None:
        self._fan_in = fan_in
        self._negative_slope = negative_slope
        self._nonlinearity = nonlinearity

    def __call__(self, param: Tensor, block: Any = None) -> None:
        fi, _ = _fans(param.shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self._nonlinearity, self._negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        sample = jax.random.uniform(
            _rng.next_key(), tuple(param.shape), jnp.float32, -limit, limit
        )
        param.set_value(sample.astype(param.dtype))


class Assign(Initializer):
    def __init__(self, value: Any) -> None:
        self.value = value

    def __call__(self, param: Tensor, block: Any = None) -> None:
        arr = self.value.numpy() if hasattr(self.value, "numpy") else np.asarray(self.value)
        param.set_value(arr.astype(np.dtype(jnp.dtype(param.dtype).name)) if arr.dtype != param.dtype else arr)


class Dirac(Initializer):
    def __init__(self, groups: int = 1) -> None:
        self.groups = groups

    def __call__(self, param: Tensor, block: Any = None) -> None:
        shape = param.shape
        arr = np.zeros(shape, dtype=np.float32)
        out_per_group = shape[0] // self.groups
        mid = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                arr[(g * out_per_group + i, i, *mid)] = 1.0
        param.set_value(arr)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0) -> None:
        self.gain = gain

    def __call__(self, param: Tensor, block: Any = None) -> None:
        shape = tuple(param.shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(_rng.next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        param.set_value((self.gain * q[:rows, :cols]).reshape(shape))
