"""Llama-2 family — the flagship model (BASELINE config #3: Llama-2 7B
pretrain, target > 2500 tokens/sec/chip on v5p).

TPU-first design decisions:
- bf16 params/activations by default; fp32 RMSNorm accumulation.
- Attention through ``nn.functional.flashmask_attention`` → Pallas kernel on
  TPU, XLA fallback elsewhere.
- GQA (num_key_value_heads < num_attention_heads) supported.
- Sharding is declarative: ``llama_shard_fn`` assigns (mesh, placements) per
  parameter for the [dp/fsdp, mp] mesh — Megatron TP layout (column-parallel
  qkv/gate/up, row-parallel o/down, vocab-parallel embedding), matching the
  reference's ``fleet/layers/mpu/mp_layers.py`` semantics but lowered through
  GSPMD instead of explicit NCCL collectives. Sequence parallelism falls out
  of sequence-dim activation constraints (``mark_activation_sharding``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.generation import GenerationMixin
from paddle_tpu.incubate.nn.functional import fused_rotary_position_embedding
from paddle_tpu.kernels.fused import count_dispatch
from paddle_tpu.ops.creation import arange
from paddle_tpu.ops.manipulation import concat, reshape


def _armed_tp_mesh() -> Any:
    """The serving engine's tensor-parallel mesh, if one is armed on this
    thread (``sys.modules`` gate so the single-chip path never imports the
    distributed package — same rule as block_attention's)."""
    import sys

    mod = sys.modules.get("paddle_tpu.distributed.tp")
    return mod.current_tp_mesh() if mod is not None else None


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False  # per-decoder-layer activation checkpointing
    # context parallelism: shard the SEQUENCE over the mesh's 'sep' axis and
    # run ring attention (long-context training; SURVEY §5.7)
    context_parallel: bool = False
    dtype: str = "bfloat16"

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab: int = 256) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=vocab, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128,
        )


class LlamaRotaryEmbedding(nn.Layer):
    def __init__(self, head_dim: int, max_position: int, theta: float) -> None:
        super().__init__()
        self.head_dim = head_dim
        import numpy as np

        inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
        t = np.arange(max_position, dtype=np.float32)
        freqs = np.outer(t, inv)
        emb = np.concatenate([freqs, freqs], axis=-1)
        self.register_buffer("cos_cached", Tensor(np.cos(emb)), persistable=False)
        self.register_buffer("sin_cached", Tensor(np.sin(emb)), persistable=False)

    def forward(self, seq_len: int, offset: Any = 0) -> Tuple[Tensor, Tensor]:
        if isinstance(offset, Tensor):
            # decode path: position is a traced scalar — or a [B] vector for
            # batches whose sequences sit at different lengths — so the table
            # lookup must be a dynamic lookup
            from paddle_tpu.core.dispatch import call_op
            import jax

            def sl(tab, off):
                if off.ndim == 0:
                    # true scalar (static-cache decode): one slice suffices
                    return jax.lax.dynamic_slice_in_dim(
                        tab, off.reshape(()), seq_len, axis=0
                    )
                # chunked rows: a dynamic_slice of width seq_len CLAMPS its
                # start to table_len - seq_len, which would silently rotate
                # the last chunk of a near-max-length context with wrong
                # positions — gather exact per-position rows instead (rows
                # past the table end clip to the last entry; those positions
                # are masked rows / beyond max_position anyway)
                pos = off.reshape(-1)[:, None] + jnp.arange(seq_len)[None, :]
                per = tab[jnp.clip(pos, 0, tab.shape[0] - 1)]
                return per[:, :, None, :]  # [B, s, 1, D] broadcasts over heads

            return (
                call_op("rope_table_slice", sl, self.cos_cached, offset),
                call_op("rope_table_slice", sl, self.sin_cached, offset),
            )
        return (
            self.cos_cached[offset : offset + seq_len],
            self.sin_cached[offset : offset + seq_len],
        )


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig) -> None:
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        bias = False
        self.q_proj = nn.Linear(self.hidden_size, self.num_heads * self.head_dim, bias_attr=bias)
        self.k_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=bias)
        self.v_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=bias)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, self.hidden_size, bias_attr=bias)
        self.rotary_emb = LlamaRotaryEmbedding(
            self.head_dim, config.max_position_embeddings, config.rope_theta
        )

    def forward(
        self,
        hidden_states: Tensor,
        startend_row_indices: Optional[Tensor] = None,
        past_key_value: Optional[Tuple[Tensor, Tensor]] = None,
        use_cache: bool = False,
        cache_position: Optional[Tensor] = None,
    ) -> Any:
        b, s, _ = hidden_states.shape
        q = reshape(self.q_proj(hidden_states), [b, s, self.num_heads, self.head_dim])
        k = reshape(self.k_proj(hidden_states), [b, s, self.num_kv_heads, self.head_dim])
        v = reshape(self.v_proj(hidden_states), [b, s, self.num_kv_heads, self.head_dim])
        if (
            cache_position is not None
            and past_key_value is not None
            and len(past_key_value) in (4, 5, 6, 8)
        ):
            # paged serving: past is (key_cache [NB,HK,BS,D], value_cache,
            # block_tables [B,MBS], seq_lens [B][, slot_mask [B][, q_lens
            # [B]]]) — the vLLM-style serving cache (reference
            # `block_multihead_attention_` fused_ops.yaml:45). Positions are
            # ragged per sequence: rope tables gather per-seq. The optional
            # 5th element is the continuous-batching engine's active-slot
            # mask: padded batch slots write no KV and return zeros, so the
            # step's shape stays fixed while the live batch composition
            # changes. The optional 6th element is the CHUNKED-PREFILL row
            # count: each slot carries up to ``s`` new tokens (a decode row
            # has q_lens == 1, a prompt chunk up to s) through ONE mixed
            # ragged dispatch — the engine's single compiled signature. An
            # 8-tuple past (FLAGS_kv_cache_dtype=int8) additionally carries
            # the pool's per-block-per-head fp32 scale planes; quantize-on-
            # write/dequant-on-read ride the same kernels, still one
            # signature.
            from paddle_tpu.core.tensor import Tensor as _T
            from paddle_tpu.incubate.nn.functional import (
                block_multihead_attention,
                block_multihead_chunk_attention,
            )

            kc, vc, tables, lens = past_key_value[:4]
            slot_mask = past_key_value[4] if len(past_key_value) >= 5 else None
            q_lens = past_key_value[5] if len(past_key_value) >= 6 else None
            k_scale = past_key_value[6] if len(past_key_value) == 8 else None
            v_scale = past_key_value[7] if len(past_key_value) == 8 else None
            lens_t = lens if isinstance(lens, _T) else _T(lens)
            lens_arr = lens_t._data
            cos, sin = self.rotary_emb(s, lens_t)  # ragged: [B, s, 1, D]
            count_dispatch("unfused:rope_gather")
            q, k, _ = fused_rotary_position_embedding(q, k, None, sin=sin, cos=cos)
            count_dispatch("unfused:rope_apply")
            mask_arr = slot_mask._data if isinstance(slot_mask, _T) else slot_mask
            ks_arr = k_scale._data if isinstance(k_scale, _T) else k_scale
            vs_arr = v_scale._data if isinstance(v_scale, _T) else v_scale
            if q_lens is not None:
                res = block_multihead_chunk_attention(
                    q._data,
                    k._data,
                    v._data,
                    kc._data if isinstance(kc, _T) else kc,
                    vc._data if isinstance(vc, _T) else vc,
                    tables._data if isinstance(tables, _T) else tables,
                    lens_arr,
                    q_lens._data if isinstance(q_lens, _T) else q_lens,
                    slot_mask=mask_arr,
                    key_scale=ks_arr,
                    value_scale=vs_arr,
                )
            else:
                res = block_multihead_attention(
                    q._data,
                    k._data,
                    v._data,
                    kc._data if isinstance(kc, _T) else kc,
                    vc._data if isinstance(vc, _T) else vc,
                    tables._data if isinstance(tables, _T) else tables,
                    lens_arr,
                    slot_mask=mask_arr,
                )
            if ks_arr is not None:
                out_a, kc2, vc2, ks2, vs2 = res
            else:
                out_a, kc2, vc2 = res
            count_dispatch("unfused:attend")
            out = self.o_proj(reshape(_T(out_a), [b, s, self.num_heads * self.head_dim]))
            count_dispatch("unfused:o_proj")
            if not use_cache:
                return out
            new_past = (_T(kc2), _T(vc2), tables, lens)
            if len(past_key_value) >= 5:
                new_past = new_past + (slot_mask,)
            if len(past_key_value) >= 6:
                new_past = new_past + (q_lens,)
            if ks_arr is not None:
                new_past = new_past + (_T(ks2), _T(vs2))
            return out, new_past
        if cache_position is not None and past_key_value is not None:
            # static-cache decode: past is a FIXED [B, S_max, HK, D] buffer
            # pair; append this step's K/V at cache_position and attend with a
            # length mask — one compiled program for every step (reference
            # `masked_multihead_attention_` ops.yaml:3074)
            from paddle_tpu.incubate.nn.functional import masked_multihead_attention

            cos, sin = self.rotary_emb(s, cache_position)
            q, k, _ = fused_rotary_position_embedding(q, k, None, sin=sin, cos=cos)
            out, ck, cv = masked_multihead_attention(
                q, k, v, past_key_value[0], past_key_value[1], cache_position
            )
            out = self.o_proj(reshape(out, [b, s, self.num_heads * self.head_dim]))
            return (out, (ck, cv)) if use_cache else out
        offset = past_key_value[0].shape[1] if past_key_value is not None else 0
        cos, sin = self.rotary_emb(s, offset)
        q, k, _ = fused_rotary_position_embedding(q, k, None, sin=sin, cos=cos)
        if past_key_value is not None:
            k = concat([past_key_value[0], k], axis=1)
            v = concat([past_key_value[1], v], axis=1)
        new_cache = (k, v) if use_cache else None
        if (
            self.config.context_parallel
            and not use_cache
            and past_key_value is None  # ring assumes sq == sk (no prefix KV)
        ):
            from paddle_tpu.distributed.mesh import get_mesh

            mesh = get_mesh()
            if (
                mesh is not None
                and "sep" in mesh.dim_names
                and mesh.get_dim_size("sep") > 1
            ):
                if startend_row_indices is not None:
                    raise NotImplementedError(
                        "FlashMask + context parallelism is not supported; "
                        "ring attention exchanges KV blocks in ring order"
                    )
                out = F.ring_flash_attention(q, k, v, causal=True)
                out = reshape(out, [b, s, self.num_heads * self.head_dim])
                return self.o_proj(out)
        out = F.flashmask_attention(
            q, k, v, startend_row_indices=startend_row_indices, causal=True
        )
        out = reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if use_cache:
            return out, new_cache
        return out

    def forward_paged_fused(
        self,
        hidden_states: Tensor,  # pre-normed [B, s, H] (norm fused upstream)
        past_key_value: Tuple[Any, ...],  # the engine's 6-tuple paged past
        cos: Tensor,  # [B, s, 1, D] offset-gathered rope rows (shared by
        sin: Tensor,  # every layer — gathered ONCE per step by the caller)
    ) -> Tuple[Tensor, Tuple[Any, ...]]:
        """The fused decode layer's attention half: qkv projections feed the
        rope-fused paged kernel (q's rotation runs inside the block walk, k's
        fuses into the cache-append scatter), so the per-layer rope pass +
        attention collapse to one dispatch. Under an armed tp mesh o_proj
        runs the tile-split row-parallel matmul so its all-reduce overlaps
        the next tile's compute."""
        from paddle_tpu.core.tensor import Tensor as _T
        from paddle_tpu.incubate.nn.functional import (
            block_multihead_chunk_attention_fused,
        )

        b, s, _ = hidden_states.shape
        q = reshape(self.q_proj(hidden_states), [b, s, self.num_heads, self.head_dim])
        k = reshape(self.k_proj(hidden_states), [b, s, self.num_kv_heads, self.head_dim])
        v = reshape(self.v_proj(hidden_states), [b, s, self.num_kv_heads, self.head_dim])
        if len(past_key_value) == 8:
            kc, vc, tables, lens, slot_mask, q_lens, k_scale, v_scale = past_key_value
        else:
            kc, vc, tables, lens, slot_mask, q_lens = past_key_value
            k_scale = v_scale = None
        ks_arr = k_scale._data if isinstance(k_scale, _T) else k_scale
        vs_arr = v_scale._data if isinstance(v_scale, _T) else v_scale
        res = block_multihead_chunk_attention_fused(
            q._data,
            k._data,
            v._data,
            cos._data if isinstance(cos, _T) else cos,
            sin._data if isinstance(sin, _T) else sin,
            kc._data if isinstance(kc, _T) else kc,
            vc._data if isinstance(vc, _T) else vc,
            tables._data if isinstance(tables, _T) else tables,
            lens._data if isinstance(lens, _T) else lens,
            q_lens._data if isinstance(q_lens, _T) else q_lens,
            slot_mask=slot_mask._data if isinstance(slot_mask, _T) else slot_mask,
            key_scale=ks_arr,
            value_scale=vs_arr,
        )
        if ks_arr is not None:
            out_a, kc2, vc2, ks2, vs2 = res
        else:
            out_a, kc2, vc2 = res
        count_dispatch("fused:attend")
        out_t = reshape(_T(out_a), [b, s, self.num_heads * self.head_dim])
        mesh = _armed_tp_mesh()
        if mesh is None:
            out = self.o_proj(out_t)
        else:
            from paddle_tpu.distributed.tp import row_parallel_overlap_matmul

            out = _T(row_parallel_overlap_matmul(out_t._data, self.o_proj.weight._data))
        count_dispatch("fused:o_proj")
        new_past = (_T(kc2), _T(vc2), tables, lens, slot_mask, q_lens)
        if ks_arr is not None:
            new_past = new_past + (_T(ks2), _T(vs2))
        return out, new_past


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig) -> None:
        super().__init__()
        self.gate_proj = nn.Linear(config.hidden_size, config.intermediate_size, bias_attr=False)
        self.up_proj = nn.Linear(config.hidden_size, config.intermediate_size, bias_attr=False)
        self.down_proj = nn.Linear(config.intermediate_size, config.hidden_size, bias_attr=False)

    def forward(self, x: Tensor) -> Tensor:
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig) -> None:
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(
        self,
        hidden_states: Tensor,
        startend_row_indices: Optional[Tensor] = None,
        past_key_value: Any = None,
        use_cache: bool = False,
        cache_position: Optional[Tensor] = None,
    ) -> Any:
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        count_dispatch("unfused:input_norm")
        attn_out = self.self_attn(
            h, startend_row_indices, past_key_value, use_cache, cache_position
        )
        if use_cache:
            attn_out, cache = attn_out
        h = residual + attn_out
        count_dispatch("unfused:attn_residual_add")
        residual = h
        h = self.post_attention_layernorm(h)
        count_dispatch("unfused:post_attn_norm")
        h = self.mlp(h)
        count_dispatch("unfused:mlp")
        h = residual + h
        count_dispatch("unfused:mlp_residual_add")
        if use_cache:
            return h, cache
        return h


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig) -> None:
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(
        self,
        input_ids: Tensor,
        startend_row_indices: Optional[Tensor] = None,
        past_key_values: Any = None,
        use_cache: bool = False,
        cache_position: Optional[Tensor] = None,
    ) -> Any:
        if (
            cache_position is not None
            and startend_row_indices is None
            and past_key_values is not None
            and GLOBAL_FLAGS.get("use_fused_decode_layer")
            and len(past_key_values) == len(self.layers)
            and all(p is not None and len(p) in (6, 8) for p in past_key_values)
        ):
            # the continuous-batching engine's one-signature mixed ragged
            # step (6-tuple paged past): run the FUSED decode layer loop —
            # same math, fewer dispatches. generate_paged's 4/5-tuple pasts
            # and every train/prefill path stay on the layer modules below.
            return self._forward_paged_fused(input_ids, past_key_values, use_cache)
        h = self.embed_tokens(input_ids)
        count_dispatch("unfused:embed")
        new_caches = [] if use_cache else None
        use_recompute = (
            self.config.recompute
            and self.training
            and not use_cache
            and past_key_values is None
        )
        for i, layer in enumerate(self.layers):
            past = past_key_values[i] if past_key_values is not None else None
            if use_recompute:
                from paddle_tpu.distributed.fleet import recompute

                h = recompute(layer, h, startend_row_indices)
            else:
                h = layer(h, startend_row_indices, past, use_cache, cache_position)
            if use_cache:
                h, cache = h
                new_caches.append(cache)
        h = self.norm(h)
        count_dispatch("unfused:final_norm")
        if use_cache:
            return h, new_caches
        return h

    def _forward_paged_fused(
        self,
        input_ids: Tensor,
        past_key_values: Any,
        use_cache: bool,
    ) -> Any:
        """The decode step's FUSED layer loop (``FLAGS_use_fused_decode_layer``).

        The unfused step issues ~9 dispatches per layer (input norm, rope
        gather, rope apply, attend, o_proj, two residual adds, post-attention
        norm, mlp). Here the epilogues pair up into single kernels:

        - entry: token gather + embedding lookup + layer 0's input RMSNorm
          fuse into one scalar-prefetch kernel seeding BOTH the residual
          stream and the normed hidden;
        - rope rows gather ONCE per step (every layer's rotary buffers hold
          identical values — the unfused per-layer gathers are redundant);
        - per layer: the rope-fused paged-attention kernel (q rotates inside
          the block walk), then residual-add + post-attention norm as ONE
          kernel, the MLP, and residual-add + the NEXT layer's input norm as
          ONE kernel — the last layer pairs with the model's final norm, so
          the loop returns ``h`` already normed;
        - under an armed tp mesh the row-parallel matmuls (o_proj/down_proj)
          split into token tiles so each tile's all-reduce overlaps the next
          tile's compute (byte-identical: the split only partitions rows).

        Byte-identity with the unfused loop holds per backend: every fused
        op's XLA fallback is the exact unfused composition, residual adds
        commute bitwise under IEEE, and the Pallas kernels replicate the
        unfused kernels' op order.
        """
        from paddle_tpu.core.tensor import Tensor as _T
        from paddle_tpu.incubate.nn.functional import (
            fused_embed_rms_norm,
            fused_rms_norm_residual,
        )

        layers = list(self.layers)
        first = layers[0]
        residual, h = fused_embed_rms_norm(
            input_ids,
            self.embed_tokens.weight,
            first.input_layernorm.weight,
            first.input_layernorm.epsilon,
        )
        count_dispatch("fused:embed_norm")
        s = input_ids.shape[1]
        lens = past_key_values[0][3]
        lens_t = lens if isinstance(lens, _T) else _T(lens)
        cos, sin = first.self_attn.rotary_emb(s, lens_t)  # once per STEP
        count_dispatch("fused:rope_gather")
        mesh = _armed_tp_mesh()
        new_caches = [] if use_cache else None
        n = len(layers)
        for i, layer in enumerate(layers):
            attn_out, cache = layer.self_attn.forward_paged_fused(
                h, past_key_values[i], cos, sin
            )
            h, residual = fused_rms_norm_residual(
                attn_out,
                layer.post_attention_layernorm.weight,
                residual,
                layer.post_attention_layernorm.epsilon,
            )
            count_dispatch("fused:residual_norm")
            if mesh is None:
                mlp_out = layer.mlp(h)
            else:
                from paddle_tpu.distributed.tp import row_parallel_overlap_matmul

                inner = F.swiglu(layer.mlp.gate_proj(h), layer.mlp.up_proj(h))
                dw = layer.mlp.down_proj.weight
                dscale = getattr(dw, "_quant_scale", None)
                if dscale is None:
                    dw_data = dw._data
                else:
                    # weight-only int8 under tp: dequantize the LOCAL K-shard
                    # before the overlapped reduce — per-output-channel scales
                    # span the full K, so per-shard dequant-then-reduce is
                    # exact (the scale factors out of the K-sum); XLA fuses
                    # the convert into the tile matmul, no resident bf16 copy
                    dw_data = (
                        dw._data.astype(jnp.float32) * dscale[None, :]
                    ).astype(inner._data.dtype)
                mlp_out = _T(
                    row_parallel_overlap_matmul(inner._data, dw_data)
                )
            count_dispatch("fused:mlp")
            next_norm = layers[i + 1].input_layernorm if i + 1 < n else self.norm
            h, residual = fused_rms_norm_residual(
                mlp_out, next_norm.weight, residual, next_norm.epsilon
            )
            count_dispatch("fused:residual_norm")
            if use_cache:
                new_caches.append(cache)
        # h left the loop already final-normed (the last pairing used
        # self.norm's weight)
        if use_cache:
            return h, new_caches
        return h


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig) -> None:
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
        else:
            self.lm_head = None

    def forward(
        self,
        input_ids: Tensor,
        labels: Optional[Tensor] = None,
        startend_row_indices: Optional[Tensor] = None,
        past_key_values: Any = None,
        use_cache: bool = False,
        cache_position: Optional[Tensor] = None,
    ) -> Any:
        """Causal-LM forward.

        Training contract: with ``labels`` given, the return is
        ``(loss, logits_or_None)``. When ``FLAGS_use_fused_loss`` is on (the
        default) the lm-head matmul is fused into a vocab-chunked
        cross-entropy (``F.fused_linear_cross_entropy``) and the second
        element is **None** — full ``[B, S, V]`` logits are never
        materialized, so returning them would pin the very buffer the fused
        path exists to eliminate across ``backward()``. Callers that need
        training-time logits must set ``FLAGS_use_fused_loss=False``.
        Without ``labels`` the return is ``logits`` (plus caches when
        ``use_cache``), unchanged.
        """
        out = self.llama(
            input_ids, startend_row_indices, past_key_values, use_cache, cache_position
        )
        caches = None
        if use_cache:
            out, caches = out
        if labels is not None and GLOBAL_FLAGS.get("use_fused_loss"):
            if self.lm_head is not None:
                loss = F.fused_linear_cross_entropy(
                    out, self.lm_head.weight, labels, ignore_index=-100,
                    reduction="mean",
                    weight_scale=getattr(self.lm_head.weight, "_quant_scale", None),
                )
            else:
                loss = F.fused_linear_cross_entropy(
                    out, self.llama.embed_tokens.weight, labels,
                    ignore_index=-100, reduction="mean", weight_vocab_major=True,
                )
            return loss, None
        if self.lm_head is not None:
            logits = self.lm_head(out)
        else:
            logits = paddle_tpu.matmul(out, self.llama.embed_tokens.weight, transpose_y=True)
        if labels is not None:
            # F.cross_entropy upcasts to fp32 internally (stable logsumexp)
            loss = F.cross_entropy(logits, labels, ignore_index=-100, reduction="mean")
            return loss, logits
        if use_cache:
            return logits, caches
        return logits


# ---------------------------------------------------------------------------
# Sharding policy: Megatron TP + DP/FSDP over a ['dp', 'mp'] mesh
# (reference layout: mpu/mp_layers.py Column/RowParallelLinear +
# VocabParallelEmbedding; here expressed as parameter placements for GSPMD).
# ---------------------------------------------------------------------------
def llama_shard_fn(name: str, sublayer: Any, mesh: Any) -> None:
    from paddle_tpu.distributed.api import apply_placement, build_placements
    from paddle_tpu.distributed.placements import Replicate

    # the one Megatron leaf-name table, shared with the serving-TP policy
    # (distributed/tp.py tp_param_spec) so the two can never drift
    from paddle_tpu.distributed.tp import (
        COLUMN_PARALLEL_LEAVES,
        ROW_PARALLEL_LEAVES,
    )

    def put(param: Any, placements: List[Any]) -> None:
        apply_placement(param, mesh, placements)

    names = mesh.dim_names

    def plc(**kw: Any) -> List[Any]:
        return build_placements(mesh, **kw)

    cls = type(sublayer).__name__
    leaf = name.rsplit(".", 1)[-1]
    if isinstance(sublayer, nn.Embedding):
        # vocab-parallel embedding: shard vocab dim on mp; fsdp shards hidden
        put(sublayer.weight, plc(mp=0, sharding=1))
    elif isinstance(sublayer, nn.Linear):
        if leaf in COLUMN_PARALLEL_LEAVES:  # incl. lm_head: [H, V] shards V
            put(sublayer.weight, plc(mp=1, sharding=0))  # column parallel
        elif leaf in ROW_PARALLEL_LEAVES:
            put(sublayer.weight, plc(mp=0, sharding=1))  # row parallel
        else:
            put(sublayer.weight, plc(sharding=0))
        if getattr(sublayer, "bias", None) is not None:
            put(sublayer.bias, [Replicate() for _ in names])
    elif isinstance(sublayer, nn.RMSNorm):
        if sublayer.weight is not None:
            put(sublayer.weight, [Replicate() for _ in names])


def mark_activation_sharding(h: Tensor, mesh: Any, seq_parallel: bool = False) -> Tensor:
    """Constraint activations [b, s, h]: batch on dp(+sharding); sequence on mp
    when sequence-parallel (the Megatron-SP scatter, reference
    ``sequence_parallel_utils.py``) — under GSPMD this single constraint
    produces the scatter/gather pairs around TP blocks."""
    from paddle_tpu.distributed.api import shard_tensor
    from paddle_tpu.distributed.placements import Replicate, Shard

    names = mesh.dim_names
    placements: List[Any] = [Replicate() for _ in names]
    if "dp" in names:
        placements[names.index("dp")] = Shard(0)
    if "sharding" in names:
        placements[names.index("sharding")] = Shard(0)
    if seq_parallel and "mp" in names:
        placements[names.index("mp")] = Shard(1)
    return shard_tensor(h, mesh, placements)
