"""Stable Diffusion v1.5 UNet (BASELINE config #5: SD v1.5 UNet inference).

UNet2DConditionModel architecture (SD v1.5: 4-ch latents, block channels
320/640/1280/1280, 2 res layers per block, cross-attention to a 768-d text
context at the first three resolutions, GEGLU feed-forward, sinusoidal
timestep embedding → 1280-d MLP).

TPU-native: NCHW convs (XLA re-lays-out), attention through the flash path,
fp32 GroupNorm. Inference is the target workload — wrap calls in
``paddle_tpu.jit.to_static`` for the compiled denoising loop.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor

__all__ = ["UNetConfig", "UNet2DConditionModel"]


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8
    norm_num_groups: int = 32
    # cross-attention at every level except the innermost (SD v1.5 layout)
    attn_blocks: Tuple[bool, ...] = (True, True, True, False)

    @staticmethod
    def sd15() -> "UNetConfig":
        return UNetConfig()

    @staticmethod
    def tiny() -> "UNetConfig":
        return UNetConfig(
            block_out_channels=(32, 64),
            layers_per_block=1,
            cross_attention_dim=32,
            attention_head_dim=4,
            norm_num_groups=8,
            attn_blocks=(True, False),
        )


def timestep_embedding(t: Tensor, dim: int, max_period: float = 10000.0) -> Tensor:
    half = dim // 2
    freqs = paddle_tpu.exp(
        paddle_tpu.arange(half, dtype="float32") * (-math.log(max_period) / half)
    )
    args = t.astype("float32").unsqueeze(-1) * freqs.unsqueeze(0)
    return paddle_tpu.concat([paddle_tpu.cos(args), paddle_tpu.sin(args)], axis=-1)


class ResnetBlock(nn.Layer):
    def __init__(self, in_ch: int, out_ch: int, temb_ch: int, groups: int) -> None:
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_ch), in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(min(groups, out_ch), out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.shortcut = nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch else None

    def forward(self, x: Tensor, temb: Tensor) -> Tensor:
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb)).unsqueeze(-1).unsqueeze(-1)
        h = self.conv2(F.silu(self.norm2(h)))
        skip = self.shortcut(x) if self.shortcut is not None else x
        return h + skip


class CrossAttention(nn.Layer):
    def __init__(self, query_dim: int, context_dim: Optional[int], num_heads: int) -> None:
        super().__init__()
        context_dim = context_dim or query_dim
        # SD v1.5 / diffusers convention: `attention_head_dim=8` is the HEAD
        # COUNT (8 heads of dim C/8 per resolution: 40/80/160 for 320/640/1280)
        if query_dim % num_heads != 0:
            raise ValueError(f"channels {query_dim} not divisible by {num_heads} heads")
        self.num_heads = num_heads
        self.head_dim = query_dim // num_heads
        self.to_q = nn.Linear(query_dim, query_dim, bias_attr=False)
        self.to_k = nn.Linear(context_dim, query_dim, bias_attr=False)
        self.to_v = nn.Linear(context_dim, query_dim, bias_attr=False)
        self.to_out = nn.Linear(query_dim, query_dim)

    def forward(self, x: Tensor, context: Optional[Tensor] = None) -> Tensor:
        context = context if context is not None else x
        b, s, d = x.shape
        sk = context.shape[1]
        q = self.to_q(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.to_k(context).reshape([b, sk, self.num_heads, self.head_dim])
        v = self.to_v(context).reshape([b, sk, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        return self.to_out(out.reshape([b, s, d]))


class GEGLU(nn.Layer):
    def __init__(self, dim: int, inner: int) -> None:
        super().__init__()
        self.proj = nn.Linear(dim, inner * 2)

    def forward(self, x: Tensor) -> Tensor:
        h = self.proj(x)
        a, g = h.chunk(2, axis=-1)
        return a * F.gelu(g)


class BasicTransformerBlock(nn.Layer):
    def __init__(self, dim: int, context_dim: int, num_heads: int) -> None:
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = CrossAttention(dim, None, num_heads)  # self
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = CrossAttention(dim, context_dim, num_heads)  # cross
        self.norm3 = nn.LayerNorm(dim)
        self.ff = nn.Sequential(GEGLU(dim, dim * 4), nn.Linear(dim * 4, dim))

    def forward(self, x: Tensor, context: Tensor) -> Tensor:
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        return x + self.ff(self.norm3(x))


class Transformer2D(nn.Layer):
    """GroupNorm → 1x1 in-proj → transformer block over HW tokens → out-proj."""

    def __init__(self, ch: int, context_dim: int, num_heads: int, groups: int) -> None:
        super().__init__()
        self.norm = nn.GroupNorm(min(groups, ch), ch)
        self.proj_in = nn.Conv2D(ch, ch, 1)
        self.block = BasicTransformerBlock(ch, context_dim, num_heads)
        self.proj_out = nn.Conv2D(ch, ch, 1)

    def forward(self, x: Tensor, context: Tensor) -> Tensor:
        b, c, hh, ww = x.shape
        res = x
        h = self.proj_in(self.norm(x))
        h = h.reshape([b, c, hh * ww]).transpose([0, 2, 1])  # [B, HW, C]
        h = self.block(h, context)
        h = h.transpose([0, 2, 1]).reshape([b, c, hh, ww])
        return self.proj_out(h) + res


class UNet2DConditionModel(nn.Layer):
    def __init__(self, config: Optional[UNetConfig] = None) -> None:
        super().__init__()
        cfg = config or UNetConfig()
        self.config = cfg
        ch0 = cfg.block_out_channels[0]
        temb_ch = ch0 * 4
        self.conv_in = nn.Conv2D(cfg.in_channels, ch0, 3, padding=1)
        self.time_embedding = nn.Sequential(
            nn.Linear(ch0, temb_ch), nn.Silu(), nn.Linear(temb_ch, temb_ch)
        )

        g = cfg.norm_num_groups
        hd = cfg.attention_head_dim
        cd = cfg.cross_attention_dim

        # down
        self.down_resnets = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamplers = nn.LayerList()
        skip_chs = [ch0]
        ch = ch0
        for i, out_ch in enumerate(cfg.block_out_channels):
            for _ in range(cfg.layers_per_block):
                self.down_resnets.append(ResnetBlock(ch, out_ch, temb_ch, g))
                ch = out_ch
                has_attn = cfg.attn_blocks[i]
                self.down_attns.append(
                    Transformer2D(ch, cd, hd, g) if has_attn else nn.Identity()
                )
                skip_chs.append(ch)
            if i < len(cfg.block_out_channels) - 1:
                self.downsamplers.append(nn.Conv2D(ch, ch, 3, stride=2, padding=1))
                skip_chs.append(ch)
            else:
                self.downsamplers.append(nn.Identity())

        # mid
        self.mid_res1 = ResnetBlock(ch, ch, temb_ch, g)
        self.mid_attn = Transformer2D(ch, cd, hd, g)
        self.mid_res2 = ResnetBlock(ch, ch, temb_ch, g)

        # up (reverse, layers_per_block+1 resnets each, consuming skips)
        self.up_resnets = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamplers = nn.LayerList()
        rev = list(reversed(cfg.block_out_channels))
        for i, out_ch in enumerate(rev):
            has_attn = list(reversed(cfg.attn_blocks))[i]
            for _ in range(cfg.layers_per_block + 1):
                skip = skip_chs.pop()
                self.up_resnets.append(ResnetBlock(ch + skip, out_ch, temb_ch, g))
                ch = out_ch
                self.up_attns.append(
                    Transformer2D(ch, cd, hd, g) if has_attn else nn.Identity()
                )
            if i < len(rev) - 1:
                self.upsamplers.append(nn.Conv2D(ch, ch, 3, padding=1))
            else:
                self.upsamplers.append(nn.Identity())

        self.conv_norm_out = nn.GroupNorm(min(g, ch), ch)
        self.conv_out = nn.Conv2D(ch, cfg.out_channels, 3, padding=1)

    def forward(self, sample: Tensor, timestep: Tensor, encoder_hidden_states: Tensor) -> Tensor:
        cfg = self.config
        temb = timestep_embedding(timestep, cfg.block_out_channels[0])
        temb = self.time_embedding(temb)

        h = self.conv_in(sample)
        skips = [h]
        li = 0
        for i, out_ch in enumerate(cfg.block_out_channels):
            for _ in range(cfg.layers_per_block):
                h = self.down_resnets[li](h, temb)
                attn = self.down_attns[li]
                if not isinstance(attn, nn.Identity):
                    h = attn(h, encoder_hidden_states)
                skips.append(h)
                li += 1
            ds = self.downsamplers[i]
            if not isinstance(ds, nn.Identity):
                h = ds(h)
                skips.append(h)

        h = self.mid_res1(h, temb)
        h = self.mid_attn(h, encoder_hidden_states)
        h = self.mid_res2(h, temb)

        li = 0
        for i in range(len(cfg.block_out_channels)):
            for _ in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                h = paddle_tpu.concat([h, skip], axis=1)
                h = self.up_resnets[li](h, temb)
                attn = self.up_attns[li]
                if not isinstance(attn, nn.Identity):
                    h = attn(h, encoder_hidden_states)
                li += 1
            us = self.upsamplers[i]
            if not isinstance(us, nn.Identity):
                h = F.interpolate(h, scale_factor=2.0, mode="nearest")
                h = us(h)

        return self.conv_out(F.silu(self.conv_norm_out(h)))
