"""ERNIE 3.0 / BERT-style bidirectional encoder (BASELINE config #2:
ERNIE-3.0-base finetune, AMP O2).

Architecture (ERNIE 3.0 base = 12-layer post-LN BERT encoder with
token/position/segment embeddings + task-id embedding, pooler, classification
head). Attention is bidirectional ``scaled_dot_product_attention`` (flash path
on TPU); finetune classification mirrors the reference's
``ErnieForSequenceClassification``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12

    @staticmethod
    def ernie3_base() -> "ErnieConfig":
        return ErnieConfig()

    @staticmethod
    def tiny(vocab: int = 128) -> "ErnieConfig":
        return ErnieConfig(
            vocab_size=vocab, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position=128, dropout=0.0,
        )


class ErnieEmbeddings(nn.Layer):
    def __init__(self, config: ErnieConfig) -> None:
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.use_task_id = config.use_task_id
        if config.use_task_id:
            self.task_type_embeddings = nn.Embedding(
                config.task_type_vocab_size, config.hidden_size
            )
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.dropout)

    def forward(
        self,
        input_ids: Tensor,
        token_type_ids: Optional[Tensor] = None,
        position_ids: Optional[Tensor] = None,
        task_type_ids: Optional[Tensor] = None,
    ) -> Tensor:
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = paddle_tpu.arange(seq, dtype="int32").unsqueeze(0)
        h = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        if token_type_ids is not None:
            h = h + self.token_type_embeddings(token_type_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = paddle_tpu.zeros_like(input_ids)
            h = h + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(h))


class ErnieSelfAttention(nn.Layer):
    def __init__(self, config: ErnieConfig) -> None:
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.q_proj = nn.Linear(h, h)
        self.k_proj = nn.Linear(h, h)
        self.v_proj = nn.Linear(h, h)
        self.out_proj = nn.Linear(h, h)
        self.dropout = config.dropout

    def forward(self, x: Tensor, attn_mask: Optional[Tensor] = None) -> Tensor:
        b, s, h = x.shape
        shp = [b, s, self.num_heads, self.head_dim]
        q = self.q_proj(x).reshape(shp)
        k = self.k_proj(x).reshape(shp)
        v = self.v_proj(x).reshape(shp)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout, is_causal=False,
            training=self.training,
        )
        return self.out_proj(out.reshape([b, s, h]))


class ErnieLayer(nn.Layer):
    """Post-LN encoder block (BERT convention, matching the reference's
    TransformerEncoderLayer default normalize_before=False)."""

    def __init__(self, config: ErnieConfig) -> None:
        super().__init__()
        self.attn = ErnieSelfAttention(config)
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x: Tensor, attn_mask: Optional[Tensor] = None) -> Tensor:
        from paddle_tpu.flags import GLOBAL_FLAGS

        if GLOBAL_FLAGS.get("use_fused_decode_layer"):
            # Post-LN: the norm REPLACES the residual stream, so only the
            # normed output of the fused op is consumed. ``a + b`` commutes
            # bitwise under IEEE and the fallback is the exact unfused
            # composition, so flag on/off stay byte-identical per backend.
            from paddle_tpu.incubate.nn.functional import fused_layer_norm_residual

            x, _ = fused_layer_norm_residual(
                self.dropout(self.attn(x, attn_mask)),
                self.ln_1.weight, self.ln_1.bias, x, self.ln_1.epsilon,
            )
            ffn = self.fc2(F.gelu(self.fc1(x)))
            x, _ = fused_layer_norm_residual(
                self.dropout(ffn), self.ln_2.weight, self.ln_2.bias, x,
                self.ln_2.epsilon,
            )
            return x
        x = self.ln_1(x + self.dropout(self.attn(x, attn_mask)))
        ffn = self.fc2(F.gelu(self.fc1(x)))
        return self.ln_2(x + self.dropout(ffn))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig) -> None:
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = nn.LayerList([ErnieLayer(config) for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(
        self,
        input_ids: Tensor,
        token_type_ids: Optional[Tensor] = None,
        position_ids: Optional[Tensor] = None,
        attention_mask: Optional[Tensor] = None,
        task_type_ids: Optional[Tensor] = None,
        labels: Optional[Tensor] = None,
    ) -> Tuple[Tensor, Tensor]:
        """Returns ``(sequence_output, pooled_output)``. With ``labels``
        (masked-LM pretraining; ``-100`` = unmasked/ignored) the first
        element is instead the MLM **loss** over the tied word-embedding
        head — fused vocab-chunk-wise when ``FLAGS_use_fused_loss`` is on,
        so ``[B, S, V]`` prediction scores are never materialized."""
        mask = None
        if attention_mask is not None:
            # [B, S] padding mask → additive [B, 1, 1, S]
            neg = (1.0 - attention_mask.astype("float32")) * -1e4
            mask = neg.unsqueeze(1).unsqueeze(2)
        h = self.embeddings(input_ids, token_type_ids, position_ids, task_type_ids)
        for layer in self.encoder:
            h = layer(h, mask)
        pooled = paddle_tpu.tanh(self.pooler(h[:, 0]))
        if labels is not None:
            from paddle_tpu.flags import GLOBAL_FLAGS

            w = self.embeddings.word_embeddings.weight
            if GLOBAL_FLAGS.get("use_fused_loss"):
                loss = F.fused_linear_cross_entropy(
                    h, w, labels, ignore_index=-100, reduction="mean",
                    weight_vocab_major=True,
                    weight_scale=getattr(w, "_quant_scale", None),
                )
            else:
                scores = paddle_tpu.matmul(h, w, transpose_y=True)
                loss = F.cross_entropy(scores, labels, ignore_index=-100, reduction="mean")
            return loss, pooled
        return h, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2) -> None:
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids: Tensor, token_type_ids: Optional[Tensor] = None,
                attention_mask: Optional[Tensor] = None) -> Tensor:
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))
