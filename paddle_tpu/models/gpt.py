"""GPT-3-style decoder LM (BASELINE config #4: GPT-3 13B TP+PP hybrid).

Architecture per the reference's GPT implementations (used by
``test/auto_parallel/hybrid_strategy/get_gpt_model.py`` and fleet examples):
learned position embeddings, pre-LN blocks, GELU MLP (4x), causal attention.

TPU-native: attention runs through ``paddle_tpu.nn.functional.flash_attention``
(Pallas on TPU); TP placements come from ``gpt_shard_fn`` (Megatron layout);
the pipeline form is built from ``LayerDesc``s with the embedding tied to the
output projection via ``SharedLayerDesc``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "GPTConfig",
    "GPTModel",
    "GPTForPretraining",
    "gpt_shard_fn",
    "build_gpt_pipeline",
]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 5120
    num_layers: int = 40
    num_heads: int = 40
    max_position: int = 2048
    ffn_ratio: int = 4
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5

    @staticmethod
    def gpt3_13b() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def tiny(vocab: int = 128) -> "GPTConfig":
        return GPTConfig(
            vocab_size=vocab, hidden_size=64, num_layers=2, num_heads=4, max_position=128
        )


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position, config.hidden_size)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids: Tensor, position_ids: Optional[Tensor] = None) -> Tensor:
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = paddle_tpu.arange(seq, dtype="int32").unsqueeze(0)
        h = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        return self.dropout(h)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        h = config.hidden_size
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.dropout = config.dropout

    def forward(self, x: Tensor) -> Tensor:
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out, _ = F.flash_attention(
            q, k, v, dropout=self.dropout, causal=True, training=self.training
        )
        return self.out_proj(out.reshape([b, s, h]))


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        h = config.hidden_size
        self.fc1 = nn.Linear(h, config.ffn_ratio * h)
        self.fc2 = nn.Linear(config.ffn_ratio * h, h)

    def forward(self, x: Tensor) -> Tensor:
        return self.fc2(F.gelu(self.fc1(x)))


class GPTBlock(nn.Layer):
    """Pre-LN decoder block."""

    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x: Tensor) -> Tensor:
        from paddle_tpu.flags import GLOBAL_FLAGS

        if GLOBAL_FLAGS.get("use_fused_decode_layer"):
            # residual add + ln_2 in ONE dispatch (tape backward runs the
            # standalone adjoint kernel). The fallback composition is the
            # exact unfused one, and ``a + b`` commutes bitwise under IEEE,
            # so flag on/off stay byte-identical per backend.
            from paddle_tpu.incubate.nn.functional import fused_layer_norm_residual

            attn_out = self.attn(self.ln_1(x))
            h2, x2 = fused_layer_norm_residual(
                attn_out, self.ln_2.weight, self.ln_2.bias, x, self.ln_2.epsilon
            )
            return x2 + self.mlp(h2)
        x = x + self.attn(self.ln_1(x))
        return x + self.mlp(self.ln_2(x))


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids: Tensor, position_ids: Optional[Tensor] = None) -> Tensor:
        h = self.embeddings(input_ids, position_ids)
        for layer in self.layers:
            h = layer(h)
        return self.ln_f(h)


class GPTForPretraining(nn.Layer):
    """LM head tied to the word embedding (the SharedLayerDesc pattern in the
    pipeline form)."""

    def __init__(self, config: GPTConfig) -> None:
        super().__init__()
        self.gpt = GPTModel(config)

    def forward(
        self,
        input_ids: Tensor,
        position_ids: Optional[Tensor] = None,
        labels: Optional[Tensor] = None,
    ) -> Any:
        """Without ``labels``: ``[B, S, V]`` logits (unchanged). With
        ``labels``: ``(loss, None)`` on the fused lm-head+cross-entropy path
        (``FLAGS_use_fused_loss``, tied embedding fuses vocab-major) — logits
        are never materialized — else ``(loss, logits)``."""
        h = self.gpt(input_ids, position_ids)
        w = self.gpt.embeddings.word_embeddings.weight
        if labels is not None:
            from paddle_tpu.flags import GLOBAL_FLAGS

            if GLOBAL_FLAGS.get("use_fused_loss"):
                loss = F.fused_linear_cross_entropy(
                    h, w, labels, ignore_index=-100, reduction="mean",
                    weight_vocab_major=True,
                    weight_scale=getattr(w, "_quant_scale", None),
                )
                return loss, None
            logits = paddle_tpu.matmul(h, w, transpose_y=True)
            loss = F.cross_entropy(logits, labels, ignore_index=-100, reduction="mean")
            return loss, logits
        return paddle_tpu.matmul(h, w, transpose_y=True)


def gpt_shard_fn(name: str, sublayer: Any, mesh: Any) -> None:
    """Megatron TP placements over the 'mp' axis: qkv/fc1 column-sharded,
    out_proj/fc2 row-sharded, embeddings vocab-sharded."""
    from paddle_tpu.distributed.api import apply_placement, build_placements

    if "mp" not in mesh.dim_names or mesh.get_dim_size("mp") == 1:
        return

    def put(param: Any, dim: Optional[int]) -> None:
        apply_placement(param, mesh, build_placements(mesh, mp=dim))

    if isinstance(sublayer, GPTAttention):
        put(sublayer.qkv_proj.weight, 1)
        put(sublayer.qkv_proj.bias, 0)
        put(sublayer.out_proj.weight, 0)
        put(sublayer.out_proj.bias, None)
    elif isinstance(sublayer, GPTMLP):
        put(sublayer.fc1.weight, 1)
        put(sublayer.fc1.bias, 0)
        put(sublayer.fc2.weight, 0)
        put(sublayer.fc2.bias, None)
    elif isinstance(sublayer, nn.Embedding):
        put(sublayer.weight, 0)


def build_gpt_pipeline(config: GPTConfig, num_stages: int, **pp_kwargs: Any):
    """The PP form: LayerDescs with tied embedding head
    (reference GPT-PP models built on ``PipelineLayer``)."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc,
        PipelineLayer,
        SharedLayerDesc,
    )

    def head_forward(layer: GPTEmbeddings, x: Tensor) -> Tensor:
        return paddle_tpu.matmul(x, layer.word_embeddings.weight, transpose_y=True)

    descs: List[Any] = [
        SharedLayerDesc("embed", GPTEmbeddings, None, "word_embeddings.weight", config)
    ]
    descs += [LayerDesc(GPTBlock, config) for _ in range(config.num_layers)]
    descs.append(LayerDesc(nn.LayerNorm, config.hidden_size, epsilon=config.layer_norm_epsilon))
    descs.append(
        SharedLayerDesc("embed", GPTEmbeddings, head_forward, "word_embeddings.weight", config)
    )
    return PipelineLayer(
        layers=descs, num_stages=num_stages, seg_method="layer:GPTBlock", **pp_kwargs
    )
