"""Model zoo for the BASELINE workloads (SURVEY §6):
llama (flagship), gpt, ernie/bert, moe, unet."""

from paddle_tpu.models.ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForSequenceClassification,
    ErnieModel,
)
from paddle_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTForPretraining,
    GPTModel,
    build_gpt_pipeline,
    gpt_shard_fn,
)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from paddle_tpu.models.sd_unet import UNet2DConditionModel, UNetConfig  # noqa: F401
