"""Model zoo for the BASELINE workloads (SURVEY §6):
llama (flagship), gpt, ernie/bert, moe, unet."""

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
