"""``paddle_tpu.signal`` — STFT / ISTFT.

Reference: ``python/paddle/signal.py`` (frame/overlap_add ops + stft/istft
over the fft kernels). TPU-native: framing is a gather with static frame
geometry, the FFT is XLA-native, and overlap-add is a scatter-add — the
whole transform jits as one fused program and is differentiable.

Layout parity: like the reference, ``frame`` produces
``[..., frame_length, num_frames]`` (frames as columns) and ``overlap_add``
consumes that layout.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_impl(a: jnp.ndarray, frame_length: int, hop_length: int) -> jnp.ndarray:
    """[..., T] -> [..., num_frames, frame_length] (internal row layout)."""
    n = a.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return a[..., idx]


def _overlap_add_impl(frames: jnp.ndarray, hop_length: int) -> jnp.ndarray:
    """[..., num_frames, frame_length] -> [..., T] scatter-add (internal)."""
    *lead, num, fl = frames.shape
    n = (num - 1) * hop_length + fl
    starts = jnp.arange(num) * hop_length
    idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
    out = jnp.zeros((*lead, n), frames.dtype)
    return out.at[..., idx].add(frames.reshape(*lead, num * fl))


def _prep_window(n_fft: int, win_length: Optional[int], window: Any) -> jnp.ndarray:
    """Default/center-pad the analysis window to n_fft (shared by stft/istft)."""
    wl = win_length if win_length is not None else n_fft
    if window is not None:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
    else:
        w = jnp.ones((wl,), jnp.float32)
    if wl < n_fft:
        lpad = (n_fft - wl) // 2
        w = jnp.pad(w, (lpad, n_fft - wl - lpad))
    return w


def frame(x: Any, frame_length: int, hop_length: int, axis: int = -1) -> Tensor:
    """Slice overlapping frames (reference ``signal.frame``): for the default
    ``axis=-1`` the result is ``[..., frame_length, num_frames]`` — frames as
    columns, matching paddle."""
    if axis not in (-1, getattr(x, "ndim", 1) - 1):
        raise NotImplementedError("frame supports the last axis")

    def fn(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.swapaxes(_frame_impl(a, frame_length, hop_length), -1, -2)

    return call_op("frame", fn, x)


def overlap_add(x: Any, hop_length: int, axis: int = -1) -> Tensor:
    """Inverse of :func:`frame` — input ``[..., frame_length, num_frames]``
    (reference ``signal.overlap_add``)."""

    def fn(a: jnp.ndarray) -> jnp.ndarray:
        return _overlap_add_impl(jnp.swapaxes(a, -1, -2), hop_length)

    return call_op("overlap_add", fn, x)


def stft(
    x: Any,
    n_fft: int,
    hop_length: Optional[int] = None,
    win_length: Optional[int] = None,
    window: Any = None,
    center: bool = True,
    pad_mode: str = "reflect",
    normalized: bool = False,
    onesided: bool = True,
    name: Any = None,
) -> Tensor:
    """Short-time Fourier transform (reference ``signal.stft``): input
    ``[..., T]`` → ``[..., n_fft(/2+1), num_frames]`` complex."""
    hop = hop_length if hop_length is not None else n_fft // 4
    w = _prep_window(n_fft, win_length, window)

    def fn(a: jnp.ndarray, wa: jnp.ndarray) -> jnp.ndarray:
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        frames = _frame_impl(a, n_fft, hop) * wa  # [..., num, n_fft]
        spec = (jnp.fft.rfft if onesided else jnp.fft.fft)(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, num_frames]

    return call_op("stft", fn, x, Tensor(w))


def istft(
    x: Any,
    n_fft: int,
    hop_length: Optional[int] = None,
    win_length: Optional[int] = None,
    window: Any = None,
    center: bool = True,
    normalized: bool = False,
    onesided: bool = True,
    length: Optional[int] = None,
    return_complex: bool = False,
    name: Any = None,
) -> Tensor:
    """Inverse STFT with window-envelope normalization (reference
    ``signal.istft``). ``return_complex=True`` keeps the complex time signal
    (requires ``onesided=False`` — a onesided spectrum already asserts a real
    signal, matching paddle's constraint)."""
    if return_complex and onesided:
        raise ValueError(
            "return_complex=True requires onesided=False (a onesided spectrum "
            "implies a real-valued signal)"
        )
    hop = hop_length if hop_length is not None else n_fft // 4
    w = _prep_window(n_fft, win_length, window)

    def fn(spec: jnp.ndarray, wa: jnp.ndarray) -> jnp.ndarray:
        s = jnp.swapaxes(spec, -1, -2)  # [..., num_frames, freq]
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(s, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(s, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * wa
        num, fl = frames.shape[-2], frames.shape[-1]
        out = _overlap_add_impl(frames, hop)
        starts = jnp.arange(num) * hop
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        env = jnp.zeros((out.shape[-1],), wa.dtype).at[idx].add(
            jnp.broadcast_to(wa * wa, (num, fl)).reshape(-1)
        )
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2 : out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return call_op("istft", fn, x, Tensor(w))
