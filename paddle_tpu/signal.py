"""``paddle_tpu.signal`` — STFT / ISTFT.

Reference: ``python/paddle/signal.py`` (frame/overlap_add ops + stft/istft
over the fft kernels). TPU-native: framing is a gather with static frame
geometry, the FFT is XLA-native, and overlap-add is a scatter-add — the
whole transform jits as one fused program and is differentiable.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x: Any, frame_length: int, hop_length: int, axis: int = -1) -> Tensor:
    """Slice overlapping frames (reference ``signal.frame``): the framed axis
    becomes ``(..., num_frames, frame_length)`` at ``axis``."""
    if axis not in (-1, getattr(x, "ndim", 1) - 1):
        raise NotImplementedError("frame supports the last axis")

    def fn(a: jnp.ndarray) -> jnp.ndarray:
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        return a[..., idx]  # [..., num, frame_length]

    return call_op("frame", fn, x)


def overlap_add(x: Any, hop_length: int, axis: int = -1) -> Tensor:
    """Inverse of :func:`frame` (reference ``signal.overlap_add``)."""

    def fn(a: jnp.ndarray) -> jnp.ndarray:
        *lead, num, fl = a.shape
        n = (num - 1) * hop_length + fl
        starts = jnp.arange(num) * hop_length
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        flat = a.reshape(*lead, num * fl)
        out = jnp.zeros((*lead, n), a.dtype)
        return out.at[..., idx].add(flat)

    return call_op("overlap_add", fn, x)


def stft(
    x: Any,
    n_fft: int,
    hop_length: Optional[int] = None,
    win_length: Optional[int] = None,
    window: Any = None,
    center: bool = True,
    pad_mode: str = "reflect",
    normalized: bool = False,
    onesided: bool = True,
    name: Any = None,
) -> Tensor:
    """Short-time Fourier transform (reference ``signal.stft``): input
    ``[..., T]`` → ``[..., n_fft(/2+1), num_frames]`` complex."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    if window is not None:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
    else:
        w = jnp.ones((wl,), jnp.float32)
    if wl < n_fft:  # center-pad the window to n_fft (paddle semantics)
        lpad = (n_fft - wl) // 2
        w = jnp.pad(w, (lpad, n_fft - wl - lpad))

    def fn(a: jnp.ndarray, wa: jnp.ndarray) -> jnp.ndarray:
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop
        starts = jnp.arange(num) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[..., idx] * wa  # [..., num, n_fft]
        spec = (jnp.fft.rfft if onesided else jnp.fft.fft)(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, num_frames]

    return call_op("stft", fn, x, Tensor(w) if not isinstance(w, Tensor) else w)


def istft(
    x: Any,
    n_fft: int,
    hop_length: Optional[int] = None,
    win_length: Optional[int] = None,
    window: Any = None,
    center: bool = True,
    normalized: bool = False,
    onesided: bool = True,
    length: Optional[int] = None,
    return_complex: bool = False,
    name: Any = None,
) -> Tensor:
    """Inverse STFT with window-envelope normalization (reference
    ``signal.istft``)."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    if window is not None:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
    else:
        w = jnp.ones((wl,), jnp.float32)
    if wl < n_fft:
        lpad = (n_fft - wl) // 2
        w = jnp.pad(w, (lpad, n_fft - wl - lpad))

    def fn(spec: jnp.ndarray, wa: jnp.ndarray) -> jnp.ndarray:
        s = jnp.swapaxes(spec, -1, -2)  # [..., num_frames, freq]
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(s, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(s, axis=-1).real)
        frames = frames * wa
        *lead, num, fl = frames.shape
        n = (num - 1) * hop + fl
        starts = jnp.arange(num) * hop
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        out = jnp.zeros((*lead, n), frames.dtype).at[..., idx].add(
            frames.reshape(*lead, num * fl)
        )
        env = jnp.zeros((n,), wa.dtype).at[idx].add(
            jnp.broadcast_to(wa * wa, (num, fl)).reshape(-1)
        )
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2 : n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return call_op("istft", fn, x, Tensor(w) if not isinstance(w, Tensor) else w)
