"""AMP: bf16/fp16 autocast + loss scaling (reference ``python/paddle/amp``)."""

from paddle_tpu.amp import debugging  # noqa: F401
from paddle_tpu.amp.auto_cast import amp_guard, auto_cast, decorate  # noqa: F401
from paddle_tpu.amp.grad_scaler import AmpScaler, GradScaler  # noqa: F401
