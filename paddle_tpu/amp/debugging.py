"""AMP numerical debugging (reference ``python/paddle/amp/debugging.py``):
tensor-stat collection, operator stats, and the check_numerics entry.

TPU-native: the per-op scan rides the eager dispatcher's
``FLAGS_check_nan_inf`` hook (``core/dispatch.py``) — the analog of the
reference's ``nan_inf_utils.cc`` per-kernel scan.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.flags import GLOBAL_FLAGS, set_flags

__all__ = [
    "DebugMode",
    "TensorCheckerConfig",
    "enable_tensor_checker",
    "disable_tensor_checker",
    "enable_operator_stats_collection",
    "disable_operator_stats_collection",
    "collect_operator_stats",
    "check_numerics",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 3


@dataclass
class TensorCheckerConfig:
    enable: bool = True
    debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT
    output_dir: Optional[str] = None
    checked_op_list: Optional[List[str]] = None
    skipped_op_list: Optional[List[str]] = None
    debug_step: Any = None
    stack_height_limit: int = 1


def enable_tensor_checker(checker_config: TensorCheckerConfig) -> None:
    """Turn on the per-op NaN/Inf scan (reference ``debugging.py``
    enable_tensor_checker → FLAGS_check_nan_inf)."""
    level = {
        DebugMode.CHECK_NAN_INF_AND_ABORT: 0,
        DebugMode.CHECK_NAN_INF: 1,
        DebugMode.CHECK_ALL: 3,
    }[checker_config.debug_mode]
    set_flags({"check_nan_inf": checker_config.enable, "check_nan_inf_level": level})


def disable_tensor_checker() -> None:
    set_flags({"check_nan_inf": False})


# -- operator stats ---------------------------------------------------------
_op_stats: Optional[Dict[str, Dict[str, int]]] = None


def _record_op(name: str, arrays: Any) -> None:
    if _op_stats is None:
        return
    for a in arrays:
        dt = str(getattr(a, "dtype", "other"))
        bucket = _op_stats.setdefault(dt, {})
        bucket[name] = bucket.get(name, 0) + 1


def enable_operator_stats_collection() -> None:
    """Count op calls per dtype (reference low-precision op-stat tables used
    to audit AMP coverage)."""
    global _op_stats
    _op_stats = {}
    from paddle_tpu.core import dispatch

    dispatch.op_stats_hook = _record_op


def disable_operator_stats_collection() -> Dict[str, Dict[str, int]]:
    global _op_stats
    from paddle_tpu.core import dispatch

    dispatch.op_stats_hook = None
    stats, _op_stats = _op_stats or {}, None
    # printable summary like the reference's table
    for dtype, ops in sorted(stats.items()):
        total = sum(ops.values())
        print(f"<{dtype}> total calls: {total}, distinct ops: {len(ops)}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(
    tensor: Any,
    op_type: str = "",
    var_name: str = "",
    debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
) -> Tuple[Any, Any]:
    """Scan one tensor; returns (num_nan, num_inf) and raises/warns per mode
    (reference ``debugging.py check_numerics`` → accuracy_check op)."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    num_nan = int(jnp.isnan(arr).sum())
    num_inf = int(jnp.isinf(arr).sum())
    if num_nan or num_inf:
        msg = (
            f"check_numerics: {op_type or 'tensor'} {var_name or ''} has "
            f"{num_nan} NaN and {num_inf} Inf values"
        )
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        print(msg)
    return num_nan, num_inf
