"""Dynamic loss scaling (reference ``python/paddle/amp/grad_scaler.py``
``AmpScaler:62``). On TPU bf16 training needs no scaling (same exponent range
as fp32) — the scaler defaults to pass-through unless fp16 is in use, matching
the reference's behavior of disabling scaling for bf16.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu.core.tensor import Tensor


class AmpScaler:
    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0**15,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 1,
        use_dynamic_loss_scaling: bool = True,
    ) -> None:
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer guard so manual unscale_() before step() (the grad-clip
        # pattern) doesn't divide gradients by the scale twice
        self._unscaled: set = set()

    def is_enable(self) -> bool:
        return self._enable

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer: Any) -> None:
        if not self._enable or id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        inv = 1.0 / self._scale
        found = False
        with paddle_tpu.no_grad():
            for p in optimizer._parameters:
                if p.grad is not None:
                    g = p.grad.data.astype(jnp.float32) * inv
                    finite = bool(jnp.all(jnp.isfinite(g)))
                    found = found or (not finite)
                    p.grad.set_value(g.astype(p.grad.dtype) if finite else jnp.zeros_like(p.grad.data))
        self._found_inf = found

    def step(self, optimizer: Any) -> None:
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled.discard(id(optimizer))
        self.update()

    def minimize(self, optimizer: Any, loss: Tensor) -> None:
        self.step(optimizer)

    def update(self) -> None:
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, new_init_loss_scaling: float) -> None:
        self._scale = float(new_init_loss_scaling)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self._scale = state_dict["scale"]
        self._good_steps = state_dict.get("good_steps", 0)
        self._bad_steps = state_dict.get("bad_steps", 0)


class GradScaler(AmpScaler):
    pass
