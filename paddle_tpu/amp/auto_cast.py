"""Autocast (reference ``python/paddle/amp/auto_cast.py`` ``amp_guard:459`` +
``amp_lists.py`` O1 white/black lists).

On TPU the native mixed-precision dtype is bfloat16 (MXU-native, no loss
scaling required). O1 casts matmul/conv inputs to bf16 at dispatch; O2 casts
model parameters wholesale (``decorate``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional, Set, Tuple, Union

import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype

# O1 lists (reference amp_lists.py): ops cast to low precision / kept in fp32.
WHITE_LIST: Set[str] = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "flashmask_attention", "scaled_dot_product_attention",
}
BLACK_LIST: Set[str] = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax_fn", "log_softmax", "cross_entropy_fn", "mean", "sum",
    "layer_norm_fn", "rms_norm_fn", "batch_norm_fn", "group_norm_fn",
    "cumsum", "logsumexp", "norm", "dist",
}

_amp_state = threading.local()


def _state() -> dict:
    if not hasattr(_amp_state, "cfg"):
        _amp_state.cfg = {"enabled": False, "dtype": jnp.bfloat16, "level": "O1",
                          "custom_white": set(), "custom_black": set()}
    return _amp_state.cfg


def amp_enabled() -> bool:
    return _state()["enabled"]


def amp_dtype() -> Any:
    return _state()["dtype"]


def amp_cast_inputs(op_name: str, arrays: Iterable[Any]) -> Tuple[Any, ...]:
    """Called by dispatch when autocast is active: cast white-list op float
    inputs to the amp dtype, black-list inputs to fp32."""
    cfg = _state()
    white = WHITE_LIST | cfg["custom_white"]
    black = (BLACK_LIST | cfg["custom_black"]) - cfg["custom_white"]
    target = None
    if op_name in white:
        target = cfg["dtype"]
    elif op_name in black:
        target = jnp.float32
    if target is None:
        return tuple(arrays)
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(jnp.dtype(a.dtype), jnp.floating):
            out.append(a.astype(target))
        else:
            out.append(a)
    return tuple(out)


class auto_cast:  # noqa: N801 - paddle API name
    def __init__(
        self,
        enable: bool = True,
        custom_white_list: Optional[Iterable[str]] = None,
        custom_black_list: Optional[Iterable[str]] = None,
        level: str = "O1",
        dtype: str = "bfloat16",
        use_promote: bool = True,
    ) -> None:
        self._cfg = {
            "enabled": enable,
            "dtype": convert_dtype(dtype),
            "level": level,
            "custom_white": set(custom_white_list or ()),
            "custom_black": set(custom_black_list or ()),
        }
        self._prev: Optional[dict] = None

    def __enter__(self) -> "auto_cast":
        self._prev = dict(_state())
        _amp_state.cfg = self._cfg
        return self

    def __exit__(self, *exc: Any) -> None:
        _amp_state.cfg = self._prev


amp_guard = auto_cast


def decorate(
    models: Any,
    optimizers: Any = None,
    level: str = "O1",
    dtype: str = "bfloat16",
    master_weight: Optional[bool] = None,
    save_dtype: Optional[str] = None,
    master_grad: bool = False,
    excluded_layers: Any = None,
):
    """O2 decoration (reference ``amp.decorate``): cast model params to the amp
    dtype; optimizer keeps fp32 master weights (multi_precision)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        excluded = set()
        if excluded_layers:
            excl_list = excluded_layers if isinstance(excluded_layers, (list, tuple)) else [excluded_layers]
            for m in model_list:
                for layer in m.sublayers(include_self=True):
                    for e in excl_list:
                        if isinstance(e, type) and isinstance(layer, e):
                            excluded.add(id(layer))
                        elif layer is e:
                            excluded.add(id(layer))
        from paddle_tpu.nn.layer.norm import _BatchNormBase, LayerNorm

        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if id(layer) in excluded or isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue
                for p in layer.parameters(include_sublayers=False):
                    if jnp.issubdtype(jnp.dtype(p.dtype), jnp.floating):
                        p._data = p._data.astype(convert_dtype(dtype))
        for m in model_list:
            m._dtype = convert_dtype(dtype)
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    if level == "O2":
        for opt in opt_list:
            opt._multi_precision = True
    return (models if single else model_list), (optimizers if opt_single else opt_list)
