"""Text datasets (reference ``python/paddle/text/datasets/``): parse the
reference's file formats from a LOCAL ``data_file`` (no downloader — this
environment has zero egress; point ``data_file`` at the archive/file)."""

from __future__ import annotations

import collections
import os
import re
import tarfile
from typing import Optional

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov"]


def _require(data_file: Optional[str], name: str) -> str:
    if not data_file or not os.path.exists(data_file):
        raise FileNotFoundError(
            f"{name} needs an explicit local data_file (no downloader in this "
            f"environment); got {data_file!r}"
        )
    return data_file


class UCIHousing(Dataset):
    """Reference ``uci_housing.py:51``: 13 features + 1 target, whitespace
    floats, feature-normalized over the whole file; 80/20 train/test split."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train") -> None:
        path = _require(data_file, "UCIHousing")
        raw = np.loadtxt(path, dtype=np.float32).reshape(-1, 14)
        features = raw[:, :13]
        maxs, mins, avgs = features.max(0), features.min(0), features.mean(0)
        denom = np.where(maxs - mins == 0, 1.0, maxs - mins)
        raw[:, :13] = (features - avgs) / denom
        split = int(raw.shape[0] * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]
        self.mode = mode

    def __len__(self) -> int:
        return len(self.data)

    def __getitem__(self, idx: int):
        row = self.data[idx]
        return row[:13], row[13:]


_TOKEN = re.compile(rb"[A-Za-z0-9']+")


class Imdb(Dataset):
    """Reference ``imdb.py:39``: sentiment pairs from the aclImdb tar —
    builds a frequency-cutoff vocabulary over the reviews and yields
    ``(ids int64[...], label int64)`` with 0=pos, 1=neg."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150) -> None:
        path = _require(data_file, "Imdb")
        with tarfile.open(path) as tf:
            # the vocabulary ALWAYS comes from the train split (reference
            # behavior) so train/test instances share token ids; in train
            # mode the vocab pass doubles as the doc pass (one tar scan)
            freq: collections.Counter = collections.Counter()
            docs, labels = [], []
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                is_train = re.match(r"aclImdb/train/(pos|neg)/.*\.txt$", m.name)
                wanted = re.match(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$", m.name)
                if not (is_train or wanted):
                    continue
                words = _TOKEN.findall(tf.extractfile(m).read().lower())
                if is_train:
                    freq.update(words)
                if wanted:
                    docs.append(words)
                    labels.append(0 if "/pos/" in m.name else 1)
        vocab_words = sorted(
            (w for w, c in freq.items() if c >= cutoff), key=lambda w: (-freq[w], w)
        )
        self.word_idx = {w: i for i, w in enumerate(vocab_words)}
        unk = self.word_idx[b"<unk>"] = len(self.word_idx)
        self.docs = [
            np.asarray([self.word_idx.get(w, unk) for w in d], np.int64) for d in docs
        ]
        self.labels = np.asarray(labels, np.int64)

    def __len__(self) -> int:
        return len(self.docs)

    def __getitem__(self, idx: int):
        return self.docs[idx], int(self.labels[idx])


class Imikolov(Dataset):
    """Reference ``imikolov.py``: PTB language-model n-grams. ``data_file``
    points at the ``simple-examples`` tar or a plain tokenized text file."""

    def __init__(self, data_file: Optional[str] = None, data_type: str = "NGRAM",
                 window_size: int = 5, mode: str = "train", min_word_freq: int = 50) -> None:
        path = _require(data_file, "Imikolov")
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]

        def read(fname: str):
            if tarfile.is_tarfile(path):
                with tarfile.open(path) as tf:
                    member = next(
                        (m for m in tf.getmembers() if m.name.endswith(fname)), None
                    )
                    if member is None:
                        return None
                    return tf.extractfile(member).read().decode().splitlines()
            return open(path).read().splitlines()

        lines = read(name)
        # vocabulary ALWAYS from the train file (shared ids across modes);
        # plain-text inputs have a single file serving both roles, and train
        # mode reuses the lines already read (one tar scan)
        vocab_lines = lines if mode == "train" else (read("ptb.train.txt") or lines)
        freq: collections.Counter = collections.Counter()
        for line in vocab_lines:
            freq.update(line.strip().split())
        sents = [line.strip().split() for line in lines]
        vocab = sorted(
            (w for w, c in freq.items() if c >= min_word_freq and w != "<unk>"),
            key=lambda w: (-freq[w], w),
        )
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = self.word_idx["<unk>"] = len(self.word_idx)
        bos = self.word_idx["<s>"] = len(self.word_idx)
        eos = self.word_idx["<e>"] = len(self.word_idx)
        self.data = []
        for words in sents:
            # reference wraps every sentence as <s> ... <e>
            ids = [bos] + [self.word_idx.get(w, unk) for w in words] + [eos]
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.asarray(ids[i : i + window_size], np.int64))
            else:  # SEQ
                self.data.append(np.asarray(ids, np.int64))

    def __len__(self) -> int:
        return len(self.data)

    def __getitem__(self, idx: int):
        return self.data[idx]
