"""``paddle_tpu.text`` — text utilities and datasets.

Reference: ``python/paddle/text/`` (``viterbi_decode.py`` ViterbiDecoder +
datasets). The decode math lives in the op layer (``ops/parity.py``
``viterbi_decode`` — a ``lax.scan`` max-sum DP); datasets parse local files
only (this environment has zero egress; the reference's downloader is
replaced by an explicit ``data_file`` argument).
"""

from typing import Any, Optional

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.ops.parity import viterbi_decode  # noqa: F401
from paddle_tpu.text.datasets import Imdb, Imikolov, UCIHousing  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imdb", "Imikolov"]


class ViterbiDecoder(Layer):
    """Reference ``text/viterbi_decode.py:110``: holds the transition matrix,
    decodes emission potentials to (scores, best tag paths)."""

    def __init__(self, transitions: Any, include_bos_eos_tag: bool = True,
                 name: Optional[str] = None) -> None:
        super().__init__()
        self.transitions = (
            transitions if isinstance(transitions, Tensor) else Tensor(transitions)
        )
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials: Any, lengths: Any = None):
        return viterbi_decode(
            potentials, self.transitions, lengths,
            include_bos_eos_tag=self.include_bos_eos_tag,
        )
