"""Continuous-batching decode engine over a ragged paged KV pool.

The serving-grade decode path: where ``generation.py::generate_paged`` runs
one static batch to completion (a finished sequence holds its batch slot and
KV blocks until EVERY sequence is done), this engine admits new requests into
freed slots every step and reclaims a finished sequence's blocks immediately
— the scheduling model of vLLM / the reference's serving stack, shaped for
TPU: all device shapes are FIXED (max-slots batch, dense block tables,
per-slot lengths as data), so the whole mixed workload runs through exactly
TWO compiled programs per (model, config):

- one PREFILL signature: ``[1, prompt_bucket]`` padded prompt, scattered into
  the pool via ``block_cache_prefill`` (positions past the true length are
  dropped), first token read at the true last position;
- one DECODE signature: ``[max_slots]`` tokens over the shared block pool,
  padded slots carried by an active-slot mask (they write no KV, attend over
  nothing, and the ragged Pallas kernel skips their compute — see
  ``kernels/paged_attention.py``).

Admits and evictions only rewrite HOST-side numpy state (block tables,
lengths, the active mask) that is passed to the compiled step as data — the
program never retraces as the request mix changes. "Ragged Paged Attention"
(arxiv 2604.15464) is the kernel shape; "Efficient Operation Fusion"
(arxiv 2502.17728) is why each step stays one fused program.

The block allocator is host-side Python (it runs between steps, not inside
the program), reusing ``BlockKVCache``'s accounting; admission reserves a
request's worst-case block need up front so a mid-flight decode step can
never hit pool exhaustion.

Fault tolerance: because every request's prompt and generated tokens live on
the host (``InferenceRequest``), a dispatch failure that consumed the
donated KV buffers is recoverable — ``step()`` retries with backoff through
``recover()``, which rebuilds the pools and replays every live slot from
host truth through the SAME two compiled programs (see README "Fault
tolerance"). Only exhausted retries mark the engine permanently failed.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.recompile import (
    CAUSE_FIRST_CALL,
    CAUSE_NEW_SHAPE_DTYPE,
    GLOBAL_WATCHDOG,
)
from paddle_tpu.testing.faults import InjectedFault, fault_point

__all__ = [
    "AdmissionPolicy",
    "ContinuousBatchingEngine",
    "EmptyPromptError",
    "FIFOAdmission",
    "InferenceRequest",
    "IntakeError",
    "InvalidTokenBudgetError",
    "PromptTooLongError",
    "RequestTooLongError",
    "RequestUnservableError",
]


class IntakeError(ValueError):
    """A request rejected at intake (validation), before any device work.

    Subclasses ``ValueError`` for backward compatibility with callers that
    ``except ValueError`` around :meth:`ContinuousBatchingEngine.add_request`;
    the typed subclasses exist so a serving layer can map each failure to an
    HTTP 4xx without string-matching the message."""


class EmptyPromptError(IntakeError):
    """The prompt has zero tokens."""


class InvalidTokenBudgetError(IntakeError):
    """``max_new_tokens`` is not a positive integer."""


class PromptTooLongError(IntakeError):
    """The prompt does not fit the configured ``prompt_bucket``."""


class RequestTooLongError(IntakeError):
    """prompt + ``max_new_tokens`` exceeds ``max_model_len``."""


class RequestUnservableError(IntakeError):
    """Worst-case KV demand exceeds the whole pool — no eviction can ever
    make room, so the request would wedge the FIFO head forever."""


def _engine_metrics() -> Dict[str, Any]:
    """Get-or-create the engine metric families (process-global: every engine
    in the process reports into the same Prometheus-style families)."""
    reg = _obs.GLOBAL_METRICS
    return {
        "ttft": reg.histogram(
            "engine_ttft_seconds",
            "Time from add_request to the request's first generated token.",
        ),
        "step": reg.histogram(
            "engine_decode_step_seconds",
            "Latency of one decode step over all active slots (incl. host sync).",
        ),
        "admitted": reg.counter(
            "engine_requests_admitted_total",
            "Requests admitted into a slot (prefill ran).",
        ),
        "finished": reg.counter(
            "engine_requests_finished_total",
            "Requests finished, by finish reason.",
            labelnames=("reason",),
        ),
        "evicted": reg.counter(
            "engine_slots_evicted_total",
            "Slot evictions: a finished sequence's KV blocks reclaimed to the pool.",
        ),
        "queue": reg.gauge(
            "engine_queue_depth", "Requests waiting for a slot (FIFO)."
        ),
        "active": reg.gauge(
            "engine_active_slots", "Slots holding a live (mid-decode) request."
        ),
        "blocks_alloc": reg.gauge(
            "engine_kv_blocks_allocated", "KV pool blocks currently allocated."
        ),
        "blocks_free": reg.gauge(
            "engine_kv_blocks_free", "KV pool blocks currently free."
        ),
        "blocks_reserved": reg.gauge(
            "engine_kv_blocks_reserved",
            "Worst-case blocks reserved by live sequences (admission guarantee).",
        ),
        "recoveries": reg.counter(
            "engine_recoveries_total",
            "Step recoveries: KV buffers reallocated and live requests "
            "replayed after a dispatch failure consumed the donated caches.",
        ),
        "replayed": reg.counter(
            "engine_requests_replayed_total",
            "Live requests re-prefilled and replayed from host-side truth "
            "during a recovery.",
        ),
        "util": reg.gauge(
            "engine_kv_pool_utilization",
            "allocated/total blocks, 0..1; high-water mark tracked since reset.",
        ),
    }


class InferenceRequest:
    """One queued generation request and, after finishing, its result.

    ``priority`` / ``tenant`` / ``deadline`` are scheduling metadata consumed
    by admission policies and the serving layer; the engine itself only acts
    on ``deadline`` (an absolute ``time.perf_counter()`` instant): a request
    whose deadline passes while queued is shed before its prefill runs, and
    one that expires mid-decode is evicted with its blocks reclaimed —
    ``finish_reason == "deadline"`` either way."""

    def __init__(
        self,
        req_id: int,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int],
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> None:
        self.req_id = req_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.priority = int(priority)
        self.tenant = str(tenant)
        self.deadline = None if deadline is None else float(deadline)
        self.generated: List[int] = []
        # "stop" | "length" | "deadline" | a cancel_request() reason
        self.finish_reason: Optional[str] = None
        self.arrival_time = time.perf_counter()  # TTFT anchor
        self.admit_time: Optional[float] = None  # None until prefill succeeded
        # lifecycle timestamps the tracing layer turns into phase spans at
        # terminal time (plain floats — kept regardless of sampling)
        self.prefill_start: Optional[float] = None
        self.finish_wall: Optional[float] = None
        # sampled trace context (observability.tracing.TraceContext) set by
        # the serving frontend; None = this request is not traced
        self.trace: Optional[Any] = None
        # decode attribution: in a continuous batch a request's decode time
        # is its share of the batched steps it rode — accumulated only while
        # tracing is enabled (one cached-bool read per STEP, not per request)
        self.decode_steps = 0
        self.decode_share_s = 0.0

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) >= self.deadline

    def tokens(self) -> np.ndarray:
        """Prompt + generated tokens, the ``generate_paged`` layout."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


class AdmissionPolicy:
    """Pluggable admission order for the engine's waiting queue.

    :meth:`select` is called while a free slot exists; it returns the next
    request to admit or None to stop admitting this boundary. Contract: the
    returned request must be drawn from ``waiting`` and must satisfy
    ``can_fit`` (the engine validates both — a buggy policy fails loudly
    instead of corrupting the worst-case reservation invariant). Returning
    None even though requests fit is allowed (e.g. a pacing policy)."""

    def select(
        self,
        waiting: Sequence["InferenceRequest"],
        can_fit: Callable[["InferenceRequest"], bool],
    ) -> Optional["InferenceRequest"]:
        raise NotImplementedError


class FIFOAdmission(AdmissionPolicy):
    """Strict arrival order with no head-of-line skipping: if the head does
    not fit the pool's unreserved blocks, nothing is admitted — a large
    request can never be starved by smaller ones arriving behind it. This is
    the engine's historical default behavior."""

    def select(
        self,
        waiting: Sequence["InferenceRequest"],
        can_fit: Callable[["InferenceRequest"], bool],
    ) -> Optional["InferenceRequest"]:
        if waiting and can_fit(waiting[0]):
            return waiting[0]
        return None


class ContinuousBatchingEngine:
    """Host-side scheduler driving one jitted prefill + one jitted decode.

    ``max_slots`` bounds the live batch; ``num_blocks`` sizes the global KV
    pool shared by all slots; ``prompt_bucket`` is the single padded prompt
    length every admitted prompt is chunked into (one prefill signature).
    """

    def __init__(
        self,
        model: Any,
        max_slots: int = 4,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        prompt_bucket: int = 32,
        max_model_len: Optional[int] = None,
        max_recoveries: int = 2,
        recovery_backoff: float = 0.05,
        admission_policy: Optional[AdmissionPolicy] = None,
    ) -> None:
        from paddle_tpu.incubate.nn.functional import BlockKVCache

        cfg = model.config
        self.model = model
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.prompt_bucket = int(prompt_bucket)
        self.max_model_len = int(
            max_model_len
            or getattr(cfg, "max_position_embeddings", None)
            or self.prompt_bucket * 4
        )
        if self.prompt_bucket > self.max_model_len:
            raise ValueError(
                f"prompt_bucket ({self.prompt_bucket}) exceeds max_model_len "
                f"({self.max_model_len})"
            )
        self.max_blocks_per_seq = -(-self.max_model_len // self.block_size)
        self.num_blocks = int(
            num_blocks if num_blocks is not None
            else self.max_slots * self.max_blocks_per_seq
        )

        kvh = cfg.num_key_value_heads
        hd = cfg.hidden_size // cfg.num_attention_heads
        self._num_layers = cfg.num_hidden_layers
        dtype = next(iter(model.parameters())).dtype
        # cache geometry, kept so recover() can rebuild identical buffers
        # (identical shapes/dtypes -> the compiled programs are reused)
        self._kvh, self._hd, self._cache_dtype = kvh, hd, dtype
        self._cache_shape = (self.num_blocks, kvh, self.block_size, hd)
        # host-side allocator/accounting only; the device pool lives below
        self._mgr = BlockKVCache(
            self.num_blocks, self.block_size, kvh, hd,
            self.max_blocks_per_seq, dtype=dtype,
        )
        # ONE global paged pool shared by every layer's sequences would alias
        # writes across layers — each layer owns its [NB, KVH, BS, D] pair,
        # all indexed by the SAME block tables (the reference layout).
        self._caches = [
            (jnp.zeros(self._cache_shape, dtype), jnp.zeros(self._cache_shape, dtype))
            for _ in range(self._num_layers)
        ]

        # per-slot host state (rewritten freely between steps — it is DATA to
        # the compiled step, never part of its shape)
        self._slot_req: List[Optional[InferenceRequest]] = [None] * self.max_slots
        self._ntok = np.zeros((self.max_slots,), np.int32)  # tokens stored in pool
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._reserved = np.zeros((self.max_slots,), np.int64)  # admission worst case
        self._waiting: deque = deque()
        self._ids = itertools.count()
        self._policy: AdmissionPolicy = admission_policy or FIFOAdmission()

        self._named = list(model.named_parameters())
        self.stats = {
            "prefill_traces": 0, "decode_traces": 0, "steps": 0,
            "admitted": 0, "recoveries": 0,
        }
        self._metrics = _engine_metrics()
        self._update_pool_gauges()
        # On donating backends (TPU) a step that fails AFTER dispatch has
        # already consumed the donated cache buffers: allocator accounting is
        # rolled back, but the KV contents are unrecoverable. step() then
        # runs recover() — reallocate the pools and replay every live slot
        # from host-side truth — up to ``max_recoveries`` times (exponential
        # ``recovery_backoff`` between attempts) before marking the engine
        # PERMANENTLY failed. On CPU (no donation) a failed step leaves the
        # buffers intact and is safely retryable by the caller, so no
        # recovery runs. ``_broken`` means permanently failed only.
        self._broken = False
        self.max_recoveries = int(max_recoveries)
        self.recovery_backoff = float(recovery_backoff)
        # finished requests awaiting delivery: survives a failed attempt so
        # a request that finished at prefill before the decode dispatch died
        # is still delivered exactly once by the step() that succeeds
        self._pending_done: List[InferenceRequest] = []
        # per-engine "first successful compile recorded" markers: the watchdog
        # attributes each engine instance's initial trace as first_call
        self._prefill_recorded = False
        self._decode_recorded = False
        donate = jax.default_backend() != "cpu"  # donation warns (no-op) on cpu
        self._prefill_fn = jax.jit(
            self._prefill_impl, donate_argnums=(1,) if donate else ()
        )
        self._decode_fn = jax.jit(
            self._decode_impl, donate_argnums=(1,) if donate else ()
        )

    # -- pool accounting -----------------------------------------------------
    def pool_stats(self) -> Dict[str, int]:
        return {
            "total": self.num_blocks,
            "free": self._mgr.free_blocks,
            "allocated": self._mgr.blocks_allocated(),
        }

    def _update_pool_gauges(self) -> None:
        """Refresh the pool/queue gauges straight from ``pool_stats()``; called
        at every admit/evict/step boundary. With metrics off this is one
        cached-bool check — the engine's hot path stays unmeasured-free."""
        if not _obs.metrics_enabled():
            return
        s = self.pool_stats()
        m = self._metrics
        m["blocks_alloc"].set(s["allocated"])
        m["blocks_free"].set(s["free"])
        m["blocks_reserved"].set(int(self._reserved.sum()))
        m["util"].set(s["allocated"] / s["total"] if s["total"] else 0.0)
        m["queue"].set(len(self._waiting))
        m["active"].set(sum(r is not None for r in self._slot_req))

    def _unreserved_free(self) -> int:
        """Free blocks not spoken for by live sequences' worst-case growth."""
        outstanding = 0
        for slot, req in enumerate(self._slot_req):
            if req is not None:
                outstanding += int(self._reserved[slot]) - self._mgr.blocks_allocated(slot)
        return self._mgr.free_blocks - outstanding

    def _buffers_lost(self) -> bool:
        return any(
            getattr(a, "is_deleted", lambda: False)()
            for kc, vc in self._caches
            for a in (kc, vc)
        )

    def _check_usable(self) -> None:
        if self._broken:
            raise RuntimeError(
                "engine KV state was lost and recovery is exhausted (failed "
                "steps consumed the donated cache buffers "
                f"{self.max_recoveries + 1} times); build a new "
                "ContinuousBatchingEngine"
            )

    # -- request intake ------------------------------------------------------
    def validate_request(self, prompt_ids: Any, max_new_tokens: int = 32) -> np.ndarray:
        """Validate one prompt against the engine's static limits WITHOUT
        queueing anything; returns the normalized ``int32`` prompt array.
        Raises a typed :class:`IntakeError` subclass (all are ``ValueError``)
        so a serving front end can map each failure to a 4xx status. Failing
        loudly at intake beats wedging the scheduler."""
        prompt = np.asarray(
            prompt_ids._data if hasattr(prompt_ids, "_data") else prompt_ids,
            np.int32,
        ).reshape(-1)
        if prompt.size < 1:
            raise EmptyPromptError("empty prompt")
        if max_new_tokens < 1:
            raise InvalidTokenBudgetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size > self.prompt_bucket:
            raise PromptTooLongError(
                f"prompt ({prompt.size} tokens) exceeds prompt_bucket "
                f"({self.prompt_bucket}); configure a larger bucket"
            )
        if prompt.size + max_new_tokens > self.max_model_len:
            raise RequestTooLongError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_model_len ({self.max_model_len})"
            )
        worst = prompt.size + max_new_tokens - 1
        need = -(-worst // self.block_size)
        if need > self.num_blocks:
            # a request no eviction can ever make room for would sit at the
            # FIFO head forever and busy-loop run()
            raise RequestUnservableError(
                f"request needs {need} KV blocks worst-case "
                f"but the pool only has {self.num_blocks}"
            )
        return prompt

    def make_request(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> InferenceRequest:
        """Validate and construct (but do not queue) one request — the seam
        a serving layer uses to hold the handle it will stream from."""
        self._check_usable()
        prompt = self.validate_request(prompt_ids, max_new_tokens)
        return InferenceRequest(
            next(self._ids), prompt, max_new_tokens, eos_token_id,
            priority=priority, tenant=tenant, deadline=deadline,
        )

    def enqueue(self, req: InferenceRequest) -> int:
        """Queue a request built by :meth:`make_request`; returns its id.
        Intake stays open while the engine is mid-recovery — recovery is an
        engine-internal condition, not a caller error, so the request simply
        queues; only a PERMANENTLY failed engine (recovery exhausted)
        hard-rejects."""
        self._check_usable()
        self._waiting.append(req)
        self._update_pool_gauges()  # queue depth changed
        return req.req_id

    def add_request(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> int:
        """Queue one prompt; returns the request id. Raises a typed
        :class:`IntakeError` on prompts that can never be served (see
        :meth:`validate_request`)."""
        return self.enqueue(
            self.make_request(
                prompt_ids, max_new_tokens, eos_token_id,
                priority=priority, tenant=tenant, deadline=deadline,
            )
        )

    def has_work(self) -> bool:
        return bool(self._waiting) or any(r is not None for r in self._slot_req)

    @property
    def broken(self) -> bool:
        """True once recovery is exhausted and the engine is PERMANENTLY
        failed (a transient, caller-retryable step failure does not set
        this — see :meth:`step`)."""
        return self._broken

    def queue_depth(self) -> int:
        """Requests waiting for a slot (what the queue-depth gauge exports)."""
        return len(self._waiting)

    def live_requests(self) -> List[InferenceRequest]:
        """Requests currently holding a slot (mid-decode), slot order."""
        return [r for r in self._slot_req if r is not None]

    def set_admission_policy(self, policy: AdmissionPolicy) -> None:
        """Swap the admission policy (takes effect at the next boundary)."""
        self._policy = policy

    def cancel_request(
        self, req_id: int, reason: str = "cancelled"
    ) -> Optional[InferenceRequest]:
        """Targeted eviction: remove ``req_id`` wherever it lives. A queued
        request is dropped before its prefill ever runs; a mid-decode one is
        evicted from its slot with its KV blocks reclaimed immediately. The
        request (``finish_reason = reason``) is returned to THIS caller and
        will NOT also be delivered by step() — exactly-once holds with the
        cancel return value as the one delivery. Returns None when the id is
        unknown (already finished and delivered, or never queued)."""
        for req in self._waiting:
            if req.req_id == req_id:
                self._waiting.remove(req)
                req.finish_reason = reason
                req.finish_wall = time.perf_counter()
                _flight.record_event(
                    "shed_queued", req_id=req.req_id, reason=reason
                )
                self._metrics["finished"].labels(reason=reason).inc()
                self._update_pool_gauges()
                return req
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.req_id == req_id:
                req.finish_reason = reason
                self._release(slot, req)
                return req
        return None

    # -- compiled programs (each traces exactly ONCE per engine) -------------
    def _param_arrays(self) -> List[Any]:
        # re-read each call: weight updates after construction are served
        # without retraces (same shapes/dtypes -> same compiled program)
        return [p._data for _, p in self._named]

    def _prefill_impl(self, param_arrays, caches, ids, table, ln):
        """ids [1, prompt_bucket] right-padded; table [1, MBS]; ln [1].

        Dense causal forward over the padded prompt (positions >= ln only
        read earlier positions, so padding never perturbs real tokens), pour
        each layer's K/V into this sequence's pool blocks (pad positions are
        scatter-dropped), take the first greedy token at the true last row.
        """
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.incubate.nn.functional import block_cache_prefill
        from paddle_tpu.nn.layer.layers import bind_param_arrays

        self.stats["prefill_traces"] += 1  # Python side: counts TRACES only
        with bind_param_arrays(self._named, param_arrays):
            with paddle_tpu.no_grad():
                logits, dense = self.model(Tensor(ids), use_cache=True)
            new_caches = []
            for (kc, vc), (k_t, v_t) in zip(caches, dense):
                new_caches.append(
                    block_cache_prefill(kc, vc, k_t._data, v_t._data, table, ln)
                )
            row = jnp.take(logits._data[0], ln[0] - 1, axis=0)  # [V] true last
            tok = jnp.argmax(row.astype(jnp.float32)).astype(jnp.int32)
            return tok, new_caches

    def _decode_impl(self, param_arrays, caches, toks, tables, lens, active):
        """toks/lens/active [S]; tables [S, MBS]. One fused step for every
        slot: append each active slot's last token, ragged-attend, argmax."""
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.layer.layers import bind_param_arrays

        self.stats["decode_traces"] += 1  # Python side: counts TRACES only
        with bind_param_arrays(self._named, param_arrays):
            pkv = [
                (Tensor(kc), Tensor(vc), Tensor(tables), Tensor(lens), Tensor(active))
                for kc, vc in caches
            ]
            with paddle_tpu.no_grad():
                logits, new_pkv = self.model(
                    Tensor(toks[:, None]),
                    past_key_values=pkv,
                    use_cache=True,
                    cache_position=Tensor(lens),
                )
            nxt = jnp.argmax(
                logits._data[:, -1, :].astype(jnp.float32), axis=-1
            ).astype(jnp.int32)
            return nxt, [(c[0]._data, c[1]._data) for c in new_pkv]

    # -- scheduling ----------------------------------------------------------
    def _blocks_needed(self, req: InferenceRequest) -> int:
        # tokens stored by the end: prompt + (max_new - 1) appended during
        # decode (the final generated token is emitted, never appended)
        worst = req.prompt.size + req.max_new_tokens - 1
        return -(-worst // self.block_size)

    def _can_fit(self, req: InferenceRequest) -> bool:
        return self._unreserved_free() >= self._blocks_needed(req)

    def _shed_expired_queued(self, done: List[InferenceRequest]) -> None:
        """Shed queued requests whose deadline already passed — BEFORE any
        prefill is spent on them. They are delivered through the same step()
        return path as normal finishes, ``finish_reason == "deadline"``."""
        if not self._waiting:
            return
        now = time.perf_counter()
        expired = [r for r in self._waiting if r.expired(now)]
        for req in expired:
            self._waiting.remove(req)
            req.finish_reason = "deadline"
            req.finish_wall = now
            _flight.record_event(
                "shed_queued", req_id=req.req_id, reason="deadline"
            )
            self._metrics["finished"].labels(reason="deadline").inc()
            done.append(req)
        if expired:
            self._update_pool_gauges()  # queue depth changed

    def _admit_waiting(self, done: List[InferenceRequest]) -> None:
        self._shed_expired_queued(done)
        while self._waiting:
            free_slots = [i for i, r in enumerate(self._slot_req) if r is None]
            if not free_slots:
                return
            req = self._policy.select(tuple(self._waiting), self._can_fit)
            if req is None:
                return
            # a buggy policy must fail loudly, not corrupt the worst-case
            # reservation invariant the pool depends on
            if req not in self._waiting:
                raise RuntimeError(
                    f"admission policy {type(self._policy).__name__} selected "
                    "a request that is not in the waiting queue"
                )
            if not self._can_fit(req):
                raise RuntimeError(
                    f"admission policy {type(self._policy).__name__} selected "
                    f"request {req.req_id} needing {self._blocks_needed(req)} "
                    f"blocks with only {self._unreserved_free()} unreserved"
                )
            self._waiting.remove(req)
            self._admit(req, free_slots[0])
            if req.finished:  # finished at prefill (eos / max_new_tokens == 1)
                done.append(req)

    def _admit(self, req: InferenceRequest, slot: int) -> None:
        plen = req.prompt.size
        self._mgr.allocate(slot, plen)
        self._reserved[slot] = self._blocks_needed(req)
        table = jnp.asarray(self._mgr.block_table([slot]))  # [1, MBS]
        ids = np.zeros((1, self.prompt_bucket), np.int32)
        ids[0, :plen] = req.prompt
        traces_before = self.stats["prefill_traces"]
        req.prefill_start = time.perf_counter()
        try:
            fault_point("engine.prefill")
            tok, self._caches = self._prefill_fn(
                self._param_arrays(), self._caches, jnp.asarray(ids), table,
                jnp.asarray([plen], jnp.int32),
            )
        except BaseException:
            # undo the allocation so a transient device failure leaves the
            # pool accounting exactly as before this admit; whether the
            # failure is recoverable (buffers lost -> recover + retry) or
            # permanent is decided by step()'s retry loop
            self._mgr.free(slot)
            self._reserved[slot] = 0
            self._waiting.appendleft(req)  # keeps FIFO order for a retry
            raise
        if self.stats["prefill_traces"] > traces_before:
            # recorded HERE, after the jit call returned: a trace that died
            # mid-body bumped the stats counter but produced no program, and
            # the watchdog ledger must only count compiles that exist
            GLOBAL_WATCHDOG.record_compile(
                "ContinuousBatchingEngine.prefill",
                signature=f"ids[1,{self.prompt_bucket}]",
                cause=CAUSE_FIRST_CALL
                if not self._prefill_recorded
                else CAUSE_NEW_SHAPE_DTYPE,
            )
            self._prefill_recorded = True
        self.stats["admitted"] += 1
        tok = int(tok)  # device sync: the first token exists past this line
        req.admit_time = time.perf_counter()
        # black box: ids and sizes only, never prompt content
        _flight.record_event(
            "admit", req_id=req.req_id, slot=slot, prompt_len=int(plen),
            queue_depth=len(self._waiting),
        )
        self._metrics["admitted"].inc()
        self._metrics["ttft"].observe(req.admit_time - req.arrival_time)
        req.generated.append(tok)
        if req.eos_token_id is not None and tok == req.eos_token_id:
            req.finish_reason = "stop"
        elif len(req.generated) >= req.max_new_tokens:
            req.finish_reason = "length"
        if req.finished:
            self._release(slot, req)  # blocks reclaimed before the next admit
            return
        self._slot_req[slot] = req
        self._ntok[slot] = plen
        self._last_tok[slot] = tok
        self._update_pool_gauges()

    def _release(self, slot: int, req: InferenceRequest) -> None:
        # finished requests are handed back ONLY through step()'s return
        # value (run() accumulates them); the engine keeps no reference, so
        # a long-running step()-driven server never grows host memory
        self._mgr.free(slot)
        self._reserved[slot] = 0
        self._slot_req[slot] = None
        self._ntok[slot] = 0
        self._last_tok[slot] = 0
        req.finish_wall = time.perf_counter()
        _flight.record_event(
            "evict", req_id=req.req_id, slot=slot,
            reason=req.finish_reason or "unknown",
            n_generated=len(req.generated),
        )
        self._metrics["evicted"].inc()
        self._metrics["finished"].labels(reason=req.finish_reason or "unknown").inc()
        self._update_pool_gauges()

    def step(self) -> List[InferenceRequest]:
        """One engine iteration: reclaim/admit, then one decode step over all
        active slots. Returns requests that finished during this step — the
        ONLY handback: the engine keeps no reference to finished requests
        (a step()-driven server never grows host memory), so a later run()
        will not re-deliver them.

        Failure policy: a dispatch failure that left the cache buffers
        intact (no donation consumed them) re-raises immediately with host
        state rolled back — the caller may simply retry. A failure that
        consumed the donated buffers (``_buffers_lost()``; an
        :class:`InjectedFault` from a fault plan models exactly this) runs
        :meth:`recover` and retries, up to ``max_recoveries`` times with
        exponential backoff, then marks the engine permanently failed and
        re-raises."""
        self._check_usable()
        attempt = 0
        while True:
            try:
                self._step_attempt()
                break
            except BaseException as exc:
                # broad on purpose: ANY dispatch failure must be classified
                # (recoverable buffers-lost vs caller-retryable) — except an
                # operator interrupt, which is never a recovery trigger and
                # must propagate NOW, not after sleep+recover+retry; if it
                # consumed donated buffers, the next step() call recovers
                if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                    raise
                # an injected dispatch fault models the donating-backend
                # failure mode (buffers consumed by the aborted dispatch),
                # so it takes the same recovery path on every backend
                recoverable = self._buffers_lost() or isinstance(exc, InjectedFault)
                if not recoverable or attempt >= self.max_recoveries:
                    self._broken = recoverable
                    if self._broken:
                        self._dump_black_box(exc)
                    raise
                attempt += 1
                time.sleep(self.recovery_backoff * (2 ** (attempt - 1)))
                try:
                    self.recover()
                except BaseException as rexc:
                    # a dispatch failure DURING recovery (device truly dead,
                    # injected or real) leaves half-rebuilt KV — permanent
                    self._broken = True
                    self._dump_black_box(rexc)
                    raise
        # deliver everything that finished during this (possibly retried)
        # step exactly once — including prefill-finishers from an attempt
        # whose decode dispatch later died
        return self.drain_finished()

    def _dump_black_box(self, exc: BaseException) -> None:
        """The engine just became PERMANENTLY failed: write the flight
        recorder's recent-event ring to disk so the postmortem has a
        timeline. safe_dump never raises — the original exception is what
        the caller must see."""
        _flight.record_event(
            "engine_permanent_failure",
            error=f"{type(exc).__name__}: {exc}"[:200],
            live=sum(r is not None for r in self._slot_req),
            queued=len(self._waiting),
        )
        _flight.safe_dump(
            "engine_permanent_failure",
            extra={
                "error": f"{type(exc).__name__}: {exc}"[:200],
                "stats": dict(self.stats),
                "pool": self.pool_stats(),
            },
        )

    def drain_finished(self) -> List[InferenceRequest]:
        """Hand back finished-but-undelivered requests. Normally step() is
        the only delivery path; this exists for the salvage case — a step
        whose delivery was preempted by an exception (including a PERMANENT
        engine failure) leaves complete results the host already holds, and
        they must be collectable rather than stranded. Usable on a broken
        engine; exactly-once still holds (the buffer is drained)."""
        out, self._pending_done = self._pending_done, []
        return out

    def _step_attempt(self) -> None:
        """One admit+decode pass; finished requests land in
        ``_pending_done`` (never lost to an exception mid-attempt)."""
        # mid-decode deadline expiry FIRST: evict before paying for another
        # step of this slot's compute, so the freed slot/blocks are available
        # to the admit pass below in the same boundary
        now = time.perf_counter()
        for i, req in enumerate(self._slot_req):
            if req is not None and req.expired(now):
                req.finish_reason = "deadline"
                self._release(i, req)
                self._pending_done.append(req)
        self._admit_waiting(self._pending_done)
        active_slots = [i for i, r in enumerate(self._slot_req) if r is not None]
        if not active_slots:
            return
        for i in active_slots:
            self._mgr.allocate(i, 1)  # room for the token appended this step
        tables = jnp.asarray(self._mgr.block_table(range(self.max_slots)))
        lens = jnp.asarray(self._ntok)  # EXCLUDING the token being appended
        active = np.zeros((self.max_slots,), bool)
        active[active_slots] = True
        t0 = time.perf_counter()
        traces_before = self.stats["decode_traces"]
        try:
            fault_point("engine.decode")
            nxt, self._caches = self._decode_fn(
                self._param_arrays(), self._caches, jnp.asarray(self._last_tok),
                tables, lens, jnp.asarray(active),
            )
        except BaseException:
            # roll the per-step allocations back so repeated failed steps
            # can't drift mgr lengths past _ntok and break the reservation
            # invariant (_unreserved_free would over-report and over-admit)
            for i in active_slots:
                self._mgr.truncate(i, int(self._ntok[i]))
            raise
        if self.stats["decode_traces"] > traces_before:
            # recorded HERE, after the jit call returned: a trace that died
            # mid-body bumped the stats counter but produced no program, and
            # the watchdog ledger must only count compiles that exist
            GLOBAL_WATCHDOG.record_compile(
                "ContinuousBatchingEngine.decode",
                signature=f"toks[{self.max_slots}]",
                cause=CAUSE_FIRST_CALL
                if not self._decode_recorded
                else CAUSE_NEW_SHAPE_DTYPE,
            )
            self._decode_recorded = True
        self.stats["steps"] += 1
        nxt = np.asarray(nxt)  # device sync: the step's tokens are real here
        t1 = time.perf_counter()
        self._metrics["step"].observe(t1 - t0)
        if _tracing.tracing_enabled():
            # per-request decode time in a continuous batch is a SHARE of
            # the batched step it rode; accumulate the even split on every
            # active request, and emit one batch-step span (annotated with
            # slot membership) when any rider is sampled
            share = (t1 - t0) / len(active_slots)
            membership: Dict[str, int] = {}
            any_sampled = False
            for i in active_slots:
                req = self._slot_req[i]
                req.decode_steps += 1
                req.decode_share_s += share
                membership[str(i)] = req.req_id
                if req.trace is not None and req.trace.sampled:
                    any_sampled = True
            if any_sampled:
                _tracing.GLOBAL_TRACER.add_span(
                    "engine.decode_step", start_s=t0, end_s=t1,
                    attrs={
                        "slot_req_ids": membership,
                        "n_active": len(active_slots),
                        "share_s": round(share, 9),
                    },
                )
        for i in active_slots:
            req = self._slot_req[i]
            tok = int(nxt[i])
            req.generated.append(tok)
            self._ntok[i] += 1
            self._last_tok[i] = tok
            if req.eos_token_id is not None and tok == req.eos_token_id:
                req.finish_reason = "stop"
            elif len(req.generated) >= req.max_new_tokens:
                req.finish_reason = "length"
            if req.finished:
                self._release(i, req)
                self._pending_done.append(req)
        self._update_pool_gauges()  # step appended one token per active slot

    def recover(self) -> None:
        """Rebuild device KV state after a dispatch failure consumed the
        donated cache buffers: reallocate the per-layer pools, reset the
        block allocator, then re-prefill and replay every live slot from
        host-side truth (``InferenceRequest`` holds the prompt and every
        token generated so far). Request ids, emitted tokens, the waiting
        queue and pending finished deliveries are all preserved.

        The rebuilt buffers have identical shapes/dtypes, so BOTH compiled
        programs are reused — a recovery must not add compiles (the
        recompile watchdog still reports exactly 2 for this engine)."""
        live = [(i, req) for i, req in enumerate(self._slot_req) if req is not None]
        t_recover = time.perf_counter()
        _flight.record_event(
            "recovery", live=len(live), queued=len(self._waiting),
            recoveries=self.stats["recoveries"] + 1,
        )
        self._caches = [
            (
                jnp.zeros(self._cache_shape, self._cache_dtype),
                jnp.zeros(self._cache_shape, self._cache_dtype),
            )
            for _ in range(self._num_layers)
        ]
        from paddle_tpu.incubate.nn.functional import BlockKVCache

        self._mgr = BlockKVCache(
            self.num_blocks, self.block_size, self._kvh, self._hd,
            self.max_blocks_per_seq, dtype=self._cache_dtype,
        )
        self._ntok[:] = 0
        self._last_tok[:] = 0
        self._reserved[:] = 0
        self.stats["recoveries"] += 1
        self._metrics["recoveries"].inc()

        # phase 1: re-prefill each live slot's prompt (the same [1, bucket]
        # signature — compiled program reused; a retrace here would be a bug
        # and is recorded so the 2-compile invariant test catches it)
        for slot, req in live:
            plen = req.prompt.size
            self._mgr.allocate(slot, plen)
            self._reserved[slot] = self._blocks_needed(req)
            table = jnp.asarray(self._mgr.block_table([slot]))
            ids = np.zeros((1, self.prompt_bucket), np.int32)
            ids[0, :plen] = req.prompt
            traces_before = self.stats["prefill_traces"]
            _tok, self._caches = self._prefill_fn(
                self._param_arrays(), self._caches, jnp.asarray(ids), table,
                jnp.asarray([plen], jnp.int32),
            )
            if self.stats["prefill_traces"] > traces_before:
                GLOBAL_WATCHDOG.record_compile(
                    "ContinuousBatchingEngine.prefill",
                    signature=f"ids[1,{self.prompt_bucket}]",
                    cause=CAUSE_NEW_SHAPE_DTYPE,
                )
            self._ntok[slot] = plen
            # the re-emitted first token is identical by determinism; host
            # truth is authoritative either way (the request already holds it)
            self._last_tok[slot] = req.generated[0]
            self._metrics["replayed"].inc()

        # phase 2: lockstep replay of already-generated tokens through the
        # decode signature (one call per replay depth, every catching-up
        # slot active) — the KV append is the effect we need; the re-emitted
        # next tokens are discarded in favor of the recorded ones
        max_replay = max((len(req.generated) - 1 for _, req in live), default=0)
        for r in range(max_replay):
            replay_slots = [i for i, req in live if len(req.generated) - 1 > r]
            for i in replay_slots:
                self._mgr.allocate(i, 1)
            tables = jnp.asarray(self._mgr.block_table(range(self.max_slots)))
            # SNAPSHOT the host-side vectors handed to the dispatch: replay
            # never syncs (the emitted tokens are discarded), and jax's CPU
            # backend zero-copies numpy inputs — mutating _ntok/_last_tok
            # below while the async dispatch is still in flight would race
            # the aliased buffers and corrupt the replayed KV. The normal
            # step path is safe only because it syncs on nxt BEFORE mutating.
            lens = jnp.asarray(self._ntok.copy())
            toks = jnp.asarray(self._last_tok.copy())
            active = np.zeros((self.max_slots,), bool)
            active[replay_slots] = True
            traces_before = self.stats["decode_traces"]
            _nxt, self._caches = self._decode_fn(
                self._param_arrays(), self._caches, toks, tables, lens,
                jnp.asarray(active),
            )
            if self.stats["decode_traces"] > traces_before:
                GLOBAL_WATCHDOG.record_compile(
                    "ContinuousBatchingEngine.decode",
                    signature=f"toks[{self.max_slots}]",
                    cause=CAUSE_NEW_SHAPE_DTYPE,
                )
            for i in replay_slots:
                req = self._slot_req[i]
                self._ntok[i] += 1
                self._last_tok[i] = req.generated[r + 1]
        if _tracing.tracing_enabled():
            _tracing.GLOBAL_TRACER.add_span(
                "engine.recover", start_s=t_recover, end_s=time.perf_counter(),
                attrs={"replayed_slots": len(live), "replay_depth": max_replay},
            )
        self._update_pool_gauges()

    def run(self) -> Dict[int, InferenceRequest]:
        """Drain the queue; returns {req_id: request} for everything that
        finished DURING this call (results from earlier direct step() calls
        were already returned by those calls)."""
        out: Dict[int, InferenceRequest] = {}
        while self.has_work():
            for req in self.step():
                out[req.req_id] = req
        return out
