"""Continuous-batching engine over a prefix-cached, ragged paged KV pool.

The serving-grade decode path: where ``generation.py::generate_paged`` runs
one static batch to completion (a finished sequence holds its batch slot and
KV blocks until EVERY sequence is done), this engine admits new requests into
freed slots every step and reclaims a finished sequence's blocks immediately
— the scheduling model of vLLM / the reference's serving stack, shaped for
TPU: all device shapes are FIXED (max-slots batch, dense block tables,
per-slot lengths as data), so the whole mixed workload runs through exactly
ONE compiled program per (model, config):

- one unified STEP signature: ``[max_slots, chunk]`` new tokens over the
  shared block pool. A decode slot contributes one valid row; a slot still
  prefilling contributes up to ``chunk`` prompt tokens (**chunked prefill**,
  "Ragged Paged Attention" arxiv 2604.15464) — prompt chunks ride the same
  dispatch as decode rows, so a long prompt never head-of-line-blocks the
  decode batch, and the recompile watchdog reports exactly 1 signature.
  Padded slots are carried by an active mask (they write no KV, attend over
  nothing, and the ragged Pallas kernel skips their compute — see
  ``kernels/paged_attention.py``).

Admits and evictions only rewrite HOST-side numpy state (block tables,
lengths, the active mask) that is passed to the compiled step as data — the
program never retraces as the request mix changes.

**Prefix caching**: with ``FLAGS_enable_prefix_cache`` (default on), prompts
are chunked into block-aligned segments keyed by a rolling content hash, and
the longest cached prefix chain is mapped straight into an admitted
request's block table with refcounts bumped — the shared prefix is computed
once and mapped by all (``inference/prefix_cache.py``). The first divergent
block is copy-on-write: the fork is carried INTO the unified step as data
(``cow_src``/``cow_dst`` per slot), so CoW adds no compiled signature.
Eviction is LRU over zero-reference chains only — a live request can never
lose a block — and the worst-case admission reservation stays honest by
counting only non-shared blocks. At request FINISH, full blocks containing
the request's committed GENERATED tokens are registered into the cache too
(rewind-safe: speculative rewinds happen at commit time, long before
release), so a multi-turn conversation's second turn maps its first turn's
KV instead of recomputing it.

**Hierarchical KV**: with ``FLAGS_kv_host_tier_bytes`` > 0, a bounded
host-RAM tier (``inference/kv_tier.py``) sits under the prefix cache:
LRU-evicted zero-ref chain blocks are captured D2H and spilled instead of
dropped, the match walk continues across the tier boundary (including the
divergent block's partial, via prefetch-on-write), and matched spilled
chains prefetch H2D asynchronously into atomically reserved pool slots —
overlapped with the mixed ragged step through a per-slot gate: a gated
slot contributes no rows until its copies land (``is_ready`` polling at
chunk boundaries), so other slots' chunks hide the transfer. Spill and
prefetch are pure data movement outside the traced step (ONE compiled
signature holds), greedy outputs are byte-identical with the tier on or
off, and ``recover()`` drops the in-flight prefetch set while the tier
itself survives as part of the host truth replay rebuilds from.

**Speculative decoding**: with ``FLAGS_spec_decode`` (default off), a
host-side n-gram / prompt-lookup drafter (``inference/spec_decode.py``)
proposes up to K draft tokens per decode slot; the slot's step row becomes a
``1 + K``-token chunk (``[last_token, d1..dK]``) with the SAME per-row
causal ``q_lens`` semantics prompt chunks already use — drafted slots,
plain-decode slots, and prefill chunks coexist in ONE dispatch of the ONE
compiled signature (verification is pure data; the recompile watchdog still
reports exactly 1 compile per engine). The step's per-row argmax is compared
against the draft left-to-right: accepted tokens commit in bulk (their KV
was written by the very step that verified them, and the argmax after the
last accepted draft rides along as a bonus token, so a fully accepted
K-draft commits K+1 tokens for one dispatch), and the first rejection
rewinds by block-table truncation through the refcounted pool. Speculation
may transiently write into a slot's reserved headroom but never past its
worst-case admission reservation (drafts are capped at the remaining token
budget), so the admission math is untouched; greedy outputs are
byte-identical with speculation on or off.

The block allocator is host-side Python (it runs between steps, not inside
the program); admission reserves a request's worst-case PRIVATE block need
up front so a mid-flight step can never hit pool exhaustion.

**Tensor parallelism**: with ``tp > 1`` (``FLAGS_engine_tp_degree`` or the
``tp=`` kwarg) the engine shards itself over a single-axis ``['tp']`` device
mesh (``distributed/tp.py``): attention heads and the paged KV pool
partition per device along the HEAD dim (one logical block id maps to the
same slot in every shard's pool partition), projections/MLP split
Megatron-style with one all-reduce per layer, and the lm-head shards over
vocab (sharded argmax — byte-identical greedy outputs). Sharding is carried
entirely by INPUT placements (committed params and caches), so the step
still compiles exactly ONCE; the scheduler, block tables, prefix-cache
chain hashes and refcounts are host-side state and stay
replicated-by-construction — the prefix cache and speculative decoding
ride along unchanged. ``tp=1`` (the default) takes the exact single-chip
path.

Fault tolerance: because every request's prompt and generated tokens live on
the host (``InferenceRequest``), a dispatch failure that consumed the
donated KV buffers is recoverable — ``step()`` retries with backoff through
``recover()``, which rebuilds the pools (and a FRESH prefix cache: the old
chain nodes point at lost KV) and replays every live slot from host truth
through the SAME compiled program (see README "Fault tolerance"). Only
exhausted retries mark the engine permanently failed.
"""

from __future__ import annotations

import contextlib
import itertools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.inference.kv_tier import HostKVTier, HostNode
from paddle_tpu.inference.prefix_cache import ChainNode, PrefixCache, chain_digest
from paddle_tpu.inference.spec_decode import NGramDrafter, count_accepted
from paddle_tpu.observability import devprof as _devprof
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.recompile import (
    CAUSE_FIRST_CALL,
    CAUSE_NEW_SHAPE_DTYPE,
    GLOBAL_WATCHDOG,
)
from paddle_tpu.testing.faults import InjectedFault, fault_point

__all__ = [
    "AdmissionPolicy",
    "ContinuousBatchingEngine",
    "EmptyPromptError",
    "FIFOAdmission",
    "InferenceRequest",
    "IntakeError",
    "InvalidTokenBudgetError",
    "PromptTooLongError",
    "RequestTooLongError",
    "RequestUnservableError",
]


class IntakeError(ValueError):
    """A request rejected at intake (validation), before any device work.

    Subclasses ``ValueError`` for backward compatibility with callers that
    ``except ValueError`` around :meth:`ContinuousBatchingEngine.add_request`;
    the typed subclasses exist so a serving layer can map each failure to an
    HTTP 4xx without string-matching the message."""


class EmptyPromptError(IntakeError):
    """The prompt has zero tokens."""


class InvalidTokenBudgetError(IntakeError):
    """``max_new_tokens`` is not a positive integer."""


class PromptTooLongError(IntakeError):
    """The prompt does not fit the configured ``prompt_bucket`` intake cap."""


class RequestTooLongError(IntakeError):
    """prompt + ``max_new_tokens`` exceeds ``max_model_len``."""


class RequestUnservableError(IntakeError):
    """Worst-case KV demand exceeds the whole pool — no eviction can ever
    make room, so the request would wedge the FIFO head forever."""


def _engine_metrics() -> Dict[str, Any]:
    """Get-or-create the engine metric families (process-global: every engine
    in the process reports into the same Prometheus-style families)."""
    reg = _obs.GLOBAL_METRICS
    return {
        "ttft": reg.histogram(
            "engine_ttft_seconds",
            "Time from add_request to the request's first generated token.",
        ),
        "step": reg.histogram(
            "engine_decode_step_seconds",
            "Latency of one unified step over all active slots (incl. host sync).",
        ),
        "admitted": reg.counter(
            "engine_requests_admitted_total",
            "Requests admitted into a slot (prefill started).",
        ),
        "finished": reg.counter(
            "engine_requests_finished_total",
            "Requests finished, by finish reason.",
            labelnames=("reason",),
        ),
        "evicted": reg.counter(
            "engine_slots_evicted_total",
            "Slot evictions: a finished sequence's KV blocks reclaimed to the pool.",
        ),
        "queue": reg.gauge(
            "engine_queue_depth", "Requests waiting for a slot (FIFO)."
        ),
        "active": reg.gauge(
            "engine_active_slots", "Slots holding a live (mid-decode) request."
        ),
        "blocks_alloc": reg.gauge(
            "engine_kv_blocks_allocated", "KV pool blocks currently allocated."
        ),
        "blocks_free": reg.gauge(
            "engine_kv_blocks_free", "KV pool blocks currently free."
        ),
        "blocks_reserved": reg.gauge(
            "engine_kv_blocks_reserved",
            "Worst-case private blocks reserved by live sequences (admission guarantee).",
        ),
        "recoveries": reg.counter(
            "engine_recoveries_total",
            "Step recoveries: KV buffers reallocated and live requests "
            "replayed after a dispatch failure consumed the donated caches.",
        ),
        "replayed": reg.counter(
            "engine_requests_replayed_total",
            "Live requests re-prefilled and replayed from host-side truth "
            "during a recovery.",
        ),
        "util": reg.gauge(
            "engine_kv_pool_utilization",
            "Blocks held by LIVE work / total, 0..1 (evictable cached blocks "
            "excluded); high-water mark tracked since reset.",
        ),
        "prefill_tokens": reg.counter(
            "engine_prefill_tokens_computed_total",
            "Prompt tokens actually computed by prefill chunks (cache hits "
            "are NOT counted here — the shared-prefix honesty counter).",
        ),
        "spec_drafted": reg.counter(
            "spec_decode_drafted_tokens_total",
            "Draft tokens proposed by the speculative drafter and scored by "
            "the unified step.",
        ),
        "spec_accepted": reg.counter(
            "spec_decode_accepted_tokens_total",
            "Draft tokens the step's greedy argmax agreed with (committed in "
            "bulk; their KV was written by the verifying step itself).",
        ),
        "spec_rejected": reg.counter(
            "spec_decode_rejected_tokens_total",
            "Draft tokens discarded at the first disagreement (KV rewound by "
            "block-table truncation).",
        ),
        "spec_accept_rate": reg.histogram(
            "spec_decode_acceptance_rate",
            "Per-speculated-step acceptance fraction: accepted / drafted "
            "(1.0 = the whole draft committed).",
        ),
        "kv_bytes_per_token": reg.gauge(
            "kv_pool_bytes_per_token",
            "Effective KV-pool bytes stored per token across all layers "
            "(int8 pools count the payload plus their fp32 scale bytes).",
        ),
        "kv_quant": reg.counter(
            "kv_quant_dequant_total",
            "Quantized-KV plane traffic attributed per successful step: "
            "'quant' counts tokens quantized on write, 'dequant' counts "
            "slot block-walks dequantizing on read. Always 0 under bf16.",
            labelnames=("op",),
        ),
    }


def _prefetch_fold(kc, vc, dst, hk, hv):
    """One prefetched block's H2D landing: write host-tier KV planes into
    pool slot ``dst`` of one layer's (key, value) pair. Jitted per engine
    with the committed pool sharding pinned as ``out_shardings`` under tp —
    ONE tiny compiled signature regardless of how many blocks land, and the
    dispatch is asynchronous: the host returns immediately and the copy
    overlaps with other slots' compute already in the device queue. Every
    later step consumes the returned arrays, so a chunk can never read a
    block the copy has not reached — the scheduler's prefetch gate is an
    overlap optimization on top of that ordering, not the correctness.

    The third output is the gate MARKER: a scalar dependent on the updated
    cache, so its readiness implies this program (and by stream order every
    earlier fold) has executed. The gate must poll this and never a cache
    array itself — the caches are donated to the next step (or next fold)
    on TPU, and polling a consumed buffer raises; the scalar is retained
    only by the gate, so nothing can ever donate it away."""
    kc = kc.at[dst].set(hk.astype(kc.dtype))
    vc = vc.at[dst].set(hv.astype(vc.dtype))
    return kc, vc, kc[dst, 0, 0, 0]


def _prefetch_fold_q(kc, vc, ks, vs, dst, hk, hv, hks, hvs):
    """Quantized-tier variant of :func:`_prefetch_fold`: a host block
    carries int8 KV planes plus their fp32 scale rows, and all four pool
    planes land in ONE program — the scale rows can never lag the payload
    they dequantize. Same marker discipline (scalar from the updated key
    plane; the scale planes are earlier outputs of the same program, so the
    marker's readiness implies theirs)."""
    kc = kc.at[dst].set(hk.astype(kc.dtype))
    vc = vc.at[dst].set(hv.astype(vc.dtype))
    ks = ks.at[dst].set(hks.astype(ks.dtype))
    vs = vs.at[dst].set(hvs.astype(vs.dtype))
    return kc, vc, ks, vs, kc[dst, 0, 0, 0]


class InferenceRequest:
    """One queued generation request and, after finishing, its result.

    ``priority`` / ``tenant`` / ``deadline`` are scheduling metadata consumed
    by admission policies and the serving layer; the engine itself only acts
    on ``deadline`` (an absolute ``time.perf_counter()`` instant): a request
    whose deadline passes while queued is shed before its prefill runs, and
    one that expires mid-decode is evicted with its blocks reclaimed —
    ``finish_reason == "deadline"`` either way."""

    def __init__(
        self,
        req_id: int,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int],
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> None:
        self.req_id = req_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.priority = int(priority)
        self.tenant = str(tenant)
        self.deadline = None if deadline is None else float(deadline)
        self.generated: List[int] = []
        # "stop" | "length" | "deadline" | a cancel_request() reason
        self.finish_reason: Optional[str] = None
        self.arrival_time = time.perf_counter()  # TTFT anchor
        self.admit_time: Optional[float] = None  # None until the first token
        # prompt tokens served from the prefix cache at admission (0 = cold)
        self.cached_tokens = 0
        # lifecycle timestamps the tracing layer turns into phase spans at
        # terminal time (plain floats — kept regardless of sampling)
        self.prefill_start: Optional[float] = None
        self.finish_wall: Optional[float] = None
        # sampled trace context (observability.tracing.TraceContext) set by
        # the serving frontend; None = this request is not traced
        self.trace: Optional[Any] = None
        # decode attribution: in a continuous batch a request's decode time
        # is its share of the batched steps it rode — accumulated only while
        # tracing is enabled (one cached-bool read per STEP, not per request)
        self.decode_steps = 0
        self.decode_share_s = 0.0

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) >= self.deadline

    def tokens(self) -> np.ndarray:
        """Prompt + generated tokens, the ``generate_paged`` layout."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


class AdmissionPolicy:
    """Pluggable admission order for the engine's waiting queue.

    :meth:`select` is called while a free slot exists; it returns the next
    request to admit or None to stop admitting this boundary. Contract: the
    returned request must be drawn from ``waiting`` and must satisfy
    ``can_fit`` (the engine validates both — a buggy policy fails loudly
    instead of corrupting the worst-case reservation invariant). Returning
    None even though requests fit is allowed (e.g. a pacing policy)."""

    def select(
        self,
        waiting: Sequence["InferenceRequest"],
        can_fit: Callable[["InferenceRequest"], bool],
    ) -> Optional["InferenceRequest"]:
        raise NotImplementedError


class FIFOAdmission(AdmissionPolicy):
    """Strict arrival order with no head-of-line skipping: if the head does
    not fit the pool's unreserved blocks, nothing is admitted — a large
    request can never be starved by smaller ones arriving behind it. This is
    the engine's historical default behavior."""

    def select(
        self,
        waiting: Sequence["InferenceRequest"],
        can_fit: Callable[["InferenceRequest"], bool],
    ) -> Optional["InferenceRequest"]:
        if waiting and can_fit(waiting[0]):
            return waiting[0]
        return None


class ContinuousBatchingEngine:
    """Host-side scheduler driving ONE jitted unified prefill/decode step.

    ``max_slots`` bounds the live batch; ``num_blocks`` sizes the global KV
    pool shared by all slots; ``prompt_bucket`` is the intake cap on prompt
    length (prompts are chunked — the bucket no longer shapes any compiled
    program); ``prefill_chunk`` is the chunk width ``C`` of the unified
    ``[max_slots, C]`` step (default: one KV block).
    """

    def __init__(
        self,
        model: Any,
        max_slots: int = 4,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        prompt_bucket: int = 32,
        max_model_len: Optional[int] = None,
        max_recoveries: int = 2,
        recovery_backoff: float = 0.05,
        admission_policy: Optional[AdmissionPolicy] = None,
        prefill_chunk: Optional[int] = None,
        enable_prefix_cache: Optional[bool] = None,
        spec_decode: Optional[bool] = None,
        tp: Optional[int] = None,
        kv_host_tier_bytes: Optional[int] = None,
        kv_cache_dtype: Optional[str] = None,
        weight_only_int8: Optional[bool] = None,
    ) -> None:
        from paddle_tpu.incubate.nn.functional import BlockKVCache

        cfg = model.config
        self.model = model
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.prompt_bucket = int(prompt_bucket)
        self.prefill_chunk = int(prefill_chunk or self.block_size)
        if self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        self.max_model_len = int(
            max_model_len
            or getattr(cfg, "max_position_embeddings", None)
            or self.prompt_bucket * 4
        )
        if self.prompt_bucket > self.max_model_len:
            raise ValueError(
                f"prompt_bucket ({self.prompt_bucket}) exceeds max_model_len "
                f"({self.max_model_len})"
            )
        self.max_blocks_per_seq = -(-self.max_model_len // self.block_size)
        self.num_blocks = int(
            num_blocks if num_blocks is not None
            else self.max_slots * self.max_blocks_per_seq
        )

        kvh = cfg.num_key_value_heads
        hd = cfg.hidden_size // cfg.num_attention_heads
        self._num_layers = cfg.num_hidden_layers
        dtype = next(iter(model.parameters())).dtype
        # cache geometry, kept so recover() can rebuild identical buffers
        # (identical shapes/dtypes/shardings -> the compiled program is reused)
        self._kvh, self._hd, self._cache_dtype = kvh, hd, dtype
        self._cache_shape = (self.num_blocks, kvh, self.block_size, hd)
        # quantized KV plane (FLAGS_kv_cache_dtype="int8"): the pool stores
        # int8 blocks plus per-block-per-head-per-token fp32 scale planes
        # [NB, KVH, BS] addressed by the SAME physical block ids — every
        # lifecycle seam (refcount, CoW, spill/prefetch, recovery replay, tp
        # head-sharding) moves cache rows and scale rows together. "bf16"
        # (the default) leaves the whole plane byte-identical to the
        # unquantized engine: no scale planes exist anywhere.
        kvd = str(
            GLOBAL_FLAGS.get("kv_cache_dtype")
            if kv_cache_dtype is None
            else kv_cache_dtype
        )
        if kvd not in ("bf16", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'bf16' or 'int8', got {kvd!r}"
            )
        self.kv_cache_dtype = kvd
        self._quant_kv = kvd == "int8"
        if self._quant_kv:
            self._cache_dtype = jnp.int8
        self._scale_shape = (self.num_blocks, kvh, self.block_size)
        # weight-only int8 (FLAGS_weight_only_int8): quantize the MLP and
        # lm-head projection weights IN PLACE, before tp sharding, so the
        # per-output-channel scales are computed over the FULL contraction
        # dim — under GSPMD the replicated [N] scale row next to the sharded
        # int8 weight is then globally exact. Inference-only (serving owns
        # the model); the scales become extra step operands below.
        wq = bool(
            GLOBAL_FLAGS.get("weight_only_int8")
            if weight_only_int8 is None
            else weight_only_int8
        )
        self._wq_params: List[Any] = []
        if wq:
            from paddle_tpu.kernels.quant import quantize_module_weights

            self._wq_params = quantize_module_weights(model)
        # tensor parallelism: commit params + caches onto a ['tp'] mesh; the
        # sharding lives in input PLACEMENTS, never in shapes, so the one
        # compiled signature (and every host-side invariant) is unchanged
        self.tp = int(GLOBAL_FLAGS.get("engine_tp_degree") if tp is None else tp)
        if self.tp < 1:
            raise ValueError(f"engine tp degree must be >= 1, got {self.tp}")
        if self.tp > 1:
            from paddle_tpu.distributed.tp import (
                build_tp_mesh,
                kv_cache_sharding,
                shard_model_params,
                tp_shard_context,
                validate_tp,
            )

            validate_tp(self.tp, cfg.num_attention_heads, kvh)
            self._tp_mesh = build_tp_mesh(self.tp)
            self._cache_sharding = kv_cache_sharding(self._tp_mesh)
            # sharded zeros created directly on-device, each device only its
            # own shard: the full pool never exists anywhere (not host RAM,
            # not chip 0) — num_blocks is sized to the AGGREGATE HBM, and
            # recover() reallocates through this too. One tiny compiled
            # zeros program reused for every layer's k and v.
            self._shard_zeros = jax.jit(
                lambda: jnp.zeros(self._cache_shape, self._cache_dtype),
                out_shardings=self._cache_sharding,
            )
            if self._quant_kv:
                from jax.sharding import NamedSharding, PartitionSpec

                # scale planes shard on the SAME head axis as the caches:
                # every shard owns the scales for exactly its head slice
                self._scale_sharding = NamedSharding(
                    self._tp_mesh, PartitionSpec(None, "tp", None)
                )
                # ones, not zeros: quantize(zeros) -> q=0, scale=1, so an
                # empty quantized pool dequantizes to exact zeros
                self._shard_zeros_scale = jax.jit(
                    lambda: jnp.ones(self._scale_shape, jnp.float32),
                    out_shardings=self._scale_sharding,
                )
            else:
                self._scale_sharding = None
            self._tp_ctx = tp_shard_context
            # serving owns the model: params are committed onto the shard
            # group in place (Megatron column/row splits, vocab-parallel
            # embedding + lm-head)
            self._tp_split_params = shard_model_params(model, self._tp_mesh)
        else:
            self._tp_mesh = None
            self._cache_sharding = None
            self._scale_sharding = None
            self._tp_ctx = None
            self._tp_split_params = 0
        # host-side refcounted block pool; the device pool lives below
        self._mgr = BlockKVCache(
            self.num_blocks, self.block_size, kvh, hd,
            self.max_blocks_per_seq, dtype=self._cache_dtype,
        )
        self._use_prefix_cache = bool(
            GLOBAL_FLAGS.get("enable_prefix_cache")
            if enable_prefix_cache is None
            else enable_prefix_cache
        )
        # hierarchical KV: a bounded host-RAM tier under the prefix cache —
        # evicted chains spill D2H instead of dying, matches against spilled
        # chains prefetch H2D overlapped into chunked prefill. 0 = off =
        # pre-tier behavior; the tier rides the prefix cache, so it is inert
        # when the cache is disabled. The tier object SURVIVES recover()
        # (host RAM is not lost with the device pools — it is the host
        # truth recovery rebuilds from).
        tier_bytes = int(
            GLOBAL_FLAGS.get("kv_host_tier_bytes")
            if kv_host_tier_bytes is None
            else kv_host_tier_bytes
        )
        self._host_tier: Optional[HostKVTier] = None
        if tier_bytes > 0 and self._use_prefix_cache:
            self._host_tier = HostKVTier(
                tier_bytes, self._bytes_per_token() * self.block_size
            )
            # the H2D landing copy: one compiled signature per engine
            # (scalar dst + one block's [KVH, BS, D] planes), kept OFF the
            # step's watchdog ledger — prefetch is data movement, not a new
            # step signature. Donation matters on TPU (the pool must not
            # transiently double); on CPU it is a warning no-op, so skip.
            fold_kw: Dict[str, Any] = {}
            if self._cache_sharding is not None:
                # preserve the committed pool partition: a GSPMD-inferred
                # output sharding would differ from the committed inputs and
                # silently compile a SECOND step executable. The scalar gate
                # marker is replicated (it is host-polled every boundary).
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(self._tp_mesh, PartitionSpec())
                if self._quant_kv:
                    fold_kw["out_shardings"] = (
                        self._cache_sharding, self._cache_sharding,
                        self._scale_sharding, self._scale_sharding, repl,
                    )
                else:
                    fold_kw["out_shardings"] = (
                        self._cache_sharding, self._cache_sharding, repl,
                    )
            fold_impl = _prefetch_fold_q if self._quant_kv else _prefetch_fold
            fold_donate: Tuple[int, ...] = ()
            if jax.default_backend() != "cpu":
                fold_donate = (0, 1, 2, 3) if self._quant_kv else (0, 1)
            self._fold_fn = jax.jit(
                fold_impl, donate_argnums=fold_donate, **fold_kw
            )
        # per-slot prefetch gate: (marker_array, n_blocks, tokens) while an
        # H2D prefetch is in flight — the slot contributes NO rows to the
        # mixed step until the copies land (correctness is guaranteed by
        # dataflow either way; the gate is what buys the overlap: other
        # slots' chunks run while this slot's blocks are still in transit)
        self._prefetch_wait: List[Optional[Tuple[Any, int, int]]] = (
            [None] * self.max_slots
        )
        # replica observability scope: unscoped by default (single-engine
        # processes record exactly as before); a cluster replica re-binds
        # via set_replica_scope() at replica construction. Set BEFORE the
        # prefix cache exists — _new_prefix_cache() consults the scope.
        self._flight = _flight.GLOBAL_FLIGHT_RECORDER
        self._metrics_scope: Optional[_obs.MetricScope] = None
        self.replica_name: Optional[str] = None
        self._cache = self._new_prefix_cache()
        # speculative decoding: drafts ride the step's chunk axis, so the
        # draft width is capped at prefill_chunk - 1 (one row is always the
        # real last token); a 1-wide chunk cannot carry a draft at all
        self._use_spec = bool(
            GLOBAL_FLAGS.get("spec_decode") if spec_decode is None else spec_decode
        )
        self._spec_k = min(
            int(GLOBAL_FLAGS.get("spec_decode_tokens")), self.prefill_chunk - 1
        )
        if self._spec_k < 1:
            self._use_spec = False
        self._drafter = (
            NGramDrafter(int(GLOBAL_FLAGS.get("spec_decode_ngram")))
            if self._use_spec
            else None
        )
        # ONE global paged pool shared by every layer's sequences would alias
        # writes across layers — each layer owns its [NB, KVH, BS, D] pair,
        # all indexed by the SAME block tables (the reference layout).
        self._caches = [self._new_cache_pair() for _ in range(self._num_layers)]

        # per-slot host state (rewritten freely between steps — it is DATA to
        # the compiled step, never part of its shape)
        self._slot_req: List[Optional[InferenceRequest]] = [None] * self.max_slots
        self._blocks: List[List[int]] = [[] for _ in range(self.max_slots)]
        # leading prefix of _blocks owned by cache chain nodes (refs held);
        # invariant: _nodes[s][i].block == _blocks[s][i]
        self._nodes: List[List[ChainNode]] = [[] for _ in range(self.max_slots)]
        self._no_insert = [False] * self.max_slots  # stop chain growth (race)
        self._matched_blocks = np.zeros((self.max_slots,), np.int64)  # at admit
        self._pending_cow: List[Optional[Tuple[ChainNode, int, int]]] = (
            [None] * self.max_slots
        )
        self._ntok = np.zeros((self.max_slots,), np.int32)  # tokens in pool
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._reserved = np.zeros((self.max_slots,), np.int64)  # worst case
        self._waiting: deque = deque()
        self._ids = itertools.count()
        self._policy: AdmissionPolicy = admission_policy or FIFOAdmission()

        self._named = list(model.named_parameters())
        self.stats = {
            "step_traces": 0, "steps": 0, "admitted": 0, "recoveries": 0,
            "prompt_tokens_computed": 0, "prompt_tokens_reused": 0,
            "spec_steps": 0, "spec_drafted": 0, "spec_accepted": 0,
            "spec_rejected": 0, "gen_blocks_registered": 0,
        }
        self._metrics = _engine_metrics()
        self._update_pool_gauges()
        # On donating backends (TPU) a step that fails AFTER dispatch has
        # already consumed the donated cache buffers: allocator accounting is
        # rolled back, but the KV contents are unrecoverable. step() then
        # runs recover() — reallocate the pools and replay every live slot
        # from host-side truth — up to ``max_recoveries`` times (exponential
        # ``recovery_backoff`` between attempts) before marking the engine
        # PERMANENTLY failed. On CPU (no donation) a failed step leaves the
        # buffers intact and is safely retryable by the caller, so no
        # recovery runs. ``_broken`` means permanently failed only.
        self._broken = False
        self.max_recoveries = int(max_recoveries)
        self.recovery_backoff = float(recovery_backoff)
        # finished requests awaiting delivery: survives a failed attempt so
        # a request that finished before the dispatch died is still delivered
        # exactly once by the step() that succeeds
        self._pending_done: List[InferenceRequest] = []
        # per-engine "first successful compile recorded" marker: the watchdog
        # attributes each engine instance's initial trace as first_call
        self._step_recorded = False
        donate = jax.default_backend() != "cpu"  # donation warns (no-op) on cpu
        if self._tp_mesh is not None:
            # pin the OUTPUT shardings: without this the returned caches
            # carry GSPMD-inferred sharding objects that hash differently
            # from the device_put-committed inputs, and the second step
            # would compile a second executable for the same trace — the
            # silent 2x-compile the 1-compile invariant exists to catch.
            # argmax output replicated (it is host-synced every step);
            # caches come back on exactly the pool partition they went in.
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._tp_mesh, PartitionSpec())
            cs = self._cache_sharding
            if self._quant_kv:
                ss = self._scale_sharding
                cache_sh = [(cs, cs, ss, ss)] * self._num_layers
            else:
                cache_sh = [(cs, cs)] * self._num_layers
            self._step_fn = jax.jit(
                self._step_impl,
                donate_argnums=(1,) if donate else (),
                out_shardings=(repl, cache_sh),
            )
        else:
            self._step_fn = jax.jit(
                self._step_impl, donate_argnums=(1,) if donate else ()
            )
        # device-time attribution (observability/devprof.py): deterministic
        # stride sampler + bounded step-timeline ring per engine; _marks is
        # non-None only while a SAMPLED step's dispatch is in flight (the
        # off path through _dispatch reads one attribute, nothing else).
        # The analytic attribution-prior hints are flop-denominated over the
        # PADDED step shape — the compiled program computes all S*C rows and
        # walks tables bounded by max_model_len, which is what the XLA cost
        # model prices too.
        self._devprof_gate = _devprof.SampleGate()
        self._devprof_timeline = _devprof.StepTimeline()
        self._devprof_marks: Optional[Dict[str, float]] = None
        from paddle_tpu.distributed.tp import analytic_cost_hints

        self._devprof_hints = analytic_cost_hints(
            num_layers=self._num_layers,
            hidden=cfg.hidden_size,
            intermediate=getattr(cfg, "intermediate_size", 4 * cfg.hidden_size),
            vocab=getattr(cfg, "vocab_size", 0),
            tokens=self.max_slots * self.prefill_chunk,
            kv_len=self.max_model_len,
            tp=self.tp,
            dtype_bytes=jnp.dtype(self._cache_dtype).itemsize,
        )

    def _new_cache_pair(self) -> Tuple[Any, ...]:
        """One layer's (key, value) pool pair — under ``kv_cache_dtype=int8``
        a (key, value, key_scale, value_scale) QUAD, the scale planes
        ``[NB, KVH, BS]`` fp32 initialized to ONES (``quantize(zeros)`` is
        ``q=0, scale=1``, so a fresh pool dequantizes to exact zeros). Under
        a tp mesh everything is committed head-sharded (``[NB, KVH/tp, ...]``
        per shard) — the pool PARTITION: every shard holds the same logical
        block ids for its own head slice, so the host-side allocator needs
        no per-shard state. Same shapes/dtypes/shardings on every call, so
        recover()'s rebuilt pools reuse the compiled program."""
        if self._cache_sharding is not None:
            if self._quant_kv:
                return (
                    self._shard_zeros(), self._shard_zeros(),
                    self._shard_zeros_scale(), self._shard_zeros_scale(),
                )
            return self._shard_zeros(), self._shard_zeros()
        kc = jnp.zeros(self._cache_shape, self._cache_dtype)
        vc = jnp.zeros(self._cache_shape, self._cache_dtype)
        if self._quant_kv:
            ks = jnp.ones(self._scale_shape, jnp.float32)
            vs = jnp.ones(self._scale_shape, jnp.float32)
            return kc, vc, ks, vs
        return kc, vc

    @property
    def tp_degree(self) -> int:
        """Tensor-parallel degree (1 = single-chip engine)."""
        return self.tp

    def tp_stats(self) -> Dict[str, Any]:
        """Shard-group view for health/observability: the mesh devices and
        the per-shard slice of the KV pool. Per-shard accounting is
        BALANCED by construction — every shard holds the same logical
        blocks over its equal head slice — and this reports the device
        truth so a test (or a probe) can hold the claim to the buffers."""
        if self._tp_mesh is None:
            return {"tp_degree": 1}
        kc = self._caches[0][0]
        if getattr(kc, "is_deleted", lambda: False)():
            # a donating backend's failed dispatch consumed the pools; until
            # recover() rebuilds them (or forever, once permanently broken)
            # there is no device truth — /healthz must report, never raise
            return {
                "tp_degree": self.tp,
                "devices": [d.id for d in self._tp_mesh.devices.flat],
                "split_params": self._tp_split_params,
                "per_shard_cache_shape": [],
                "balanced": None,
                "buffers": "lost",
            }
        shards = sorted(
            (s.device.id, list(s.data.shape)) for s in kc.addressable_shards
        )
        per_shard = [shape for _, shape in shards]
        return {
            "tp_degree": self.tp,
            "devices": [d.id for d in self._tp_mesh.devices.flat],
            "split_params": self._tp_split_params,
            "per_shard_cache_shape": per_shard[0] if per_shard else [],
            "balanced": all(s == per_shard[0] for s in per_shard),
        }

    def devprof_stats(self) -> Dict[str, Any]:
        """Device-time attribution summary over this engine's step-timeline
        ring (what /healthz and incident snapshots embed): mean segment
        split, mean per-category device shares, measured comm share with
        its source breakdown. ``{"enabled": False, "sampled_steps": 0}``
        while ``FLAGS_devprof_sample_rate`` is 0 — valid, never raises."""
        return _devprof.summarize_timeline(self._devprof_timeline.entries())

    def _bytes_per_token(self) -> int:
        """KV bytes across all layers for one token (sizes the bytes-saved
        gauge and the host tier's per-block cost). Quantized pools count the
        TRUE footprint: the int8 payload plus one fp32 scale per (token,
        head) — ``2·L·KVH·(D+4)`` vs bf16's ``2·L·KVH·2D``, a ``2D/(D+4)``
        reduction (1.94x at D=128)."""
        if self._quant_kv:
            return 2 * self._num_layers * self._kvh * (self._hd + 4)
        return (
            2 * self._num_layers * self._kvh * self._hd
            * jnp.dtype(self._cache_dtype).itemsize
        )

    def _new_prefix_cache(self) -> Optional[PrefixCache]:
        if not self._use_prefix_cache:
            return None
        cache = PrefixCache(
            self._mgr, self.block_size, self._bytes_per_token(),
            host_tier=self._host_tier,
            capture_kv=(
                self._capture_block_kv if self._host_tier is not None else None
            ),
        )
        if self._metrics_scope is not None:
            # recover() rebuilds a fresh cache: replica attribution survives
            cache.set_replica_scope(self._metrics_scope, self._flight)
        return cache

    def _capture_block_kv(self, block: int) -> np.ndarray:
        """D2H capture of one physical block's KV across every layer —
        ``[layers, 2, KVH, BS, D]`` — for a spill. Synchronous by design:
        the copy must complete before the block's pool reference drops and
        the slot can be reallocated and overwritten (the caller holds that
        ordering). Under tensor parallelism the head shards gather here —
        the host tier always holds the full-head view."""
        if self._quant_kv:
            # quantized capture: ONE int8 ndarray [L, 2, KVH, BS, D+4] — the
            # fp32 scale rides as 4 trailing bytes per (head, token) row, so
            # the host tier's byte budget sees the true halved footprint and
            # spill/prefetch move payload + scales as one unit
            parts = []
            for kc, vc, ks, vs in self._caches:
                kv = np.asarray(jnp.stack((kc[block], vc[block])))
                sc = np.asarray(
                    jnp.stack((ks[block], vs[block])), dtype=np.float32
                )
                sc_bytes = np.ascontiguousarray(sc[..., None]).view(np.int8)
                parts.append(np.concatenate([kv, sc_bytes], axis=-1))
            return np.stack(parts)
        parts = [
            jnp.stack((kc[block], vc[block])) for kc, vc in self._caches
        ]
        return np.asarray(jnp.stack(parts))

    # -- pool accounting -----------------------------------------------------
    def pool_stats(self) -> Dict[str, Any]:
        free = self._mgr.free_blocks
        return {
            "total": self.num_blocks,
            "free": free,
            "allocated": self.num_blocks - free,
            "kv_cache_dtype": self.kv_cache_dtype,
            "bytes_per_token": self._bytes_per_token(),
            # blocks the prefix cache retains warm but surrenders under
            # pressure: reclaimable, so admission/overload math treats them
            # as headroom, not load
            "cached_reusable": (
                self._cache.evictable_blocks if self._cache is not None else 0
            ),
            # ALL cache-owned blocks (incl. chain interiors pinned by
            # children): with no live work, free + cached_blocks == total
            "cached_blocks": (
                self._cache.node_count if self._cache is not None else 0
            ),
        }

    def prefix_cache_stats(self) -> Dict[str, Any]:
        """Hit-rate / sharing signals for the serving layer (empty when the
        prefix cache is disabled)."""
        if self._cache is None:
            return {"enabled": False}
        out: Dict[str, Any] = {"enabled": True}
        out.update(self._cache.stats_snapshot())
        return out

    def kv_tier_stats(self) -> Dict[str, Any]:
        """Host-tier view for /healthz and bench records (host counters —
        valid with metrics off; ``{"enabled": False}`` when the tier is
        off, which is also the ``FLAGS_kv_host_tier_bytes=0`` default)."""
        if self._host_tier is None:
            return {"enabled": False}
        out: Dict[str, Any] = {"enabled": True}
        out.update(self._host_tier.stats_snapshot())
        return out

    def _update_pool_gauges(self) -> None:
        """Refresh the pool/queue gauges straight from ``pool_stats()``; called
        at every admit/evict/step boundary. With metrics off this is one
        cached-bool check — the engine's hot path stays unmeasured-free."""
        if not _obs.metrics_enabled():
            return
        s = self.pool_stats()
        m = self._metrics
        m["blocks_alloc"].set(s["allocated"])
        m["blocks_free"].set(s["free"])
        m["kv_bytes_per_token"].set(s["bytes_per_token"])
        m["blocks_reserved"].set(int(self._reserved.sum()))
        live = s["allocated"] - s["cached_reusable"]
        m["util"].set(live / s["total"] if s["total"] else 0.0)
        m["queue"].set(len(self._waiting))
        m["active"].set(sum(r is not None for r in self._slot_req))
        if self._cache is not None:
            self._cache.update_shared_gauge()

    def _unreserved_free(self) -> int:
        """Blocks available to new admissions: free + evictable cached,
        minus live sequences' outstanding worst-case PRIVATE growth (shared
        mapped blocks never grow — they are counted at zero)."""
        outstanding = 0
        for slot, req in enumerate(self._slot_req):
            if req is not None:
                private = len(self._blocks[slot]) - int(self._matched_blocks[slot])
                outstanding += int(self._reserved[slot]) - private
        reusable = self._cache.evictable_blocks if self._cache is not None else 0
        return self._mgr.free_blocks + reusable - outstanding

    def _buffers_lost(self) -> bool:
        return any(
            getattr(a, "is_deleted", lambda: False)()
            for entry in self._caches
            for a in entry
        )

    def _check_usable(self) -> None:
        if self._broken:
            raise RuntimeError(
                "engine KV state was lost and recovery is exhausted (failed "
                "steps consumed the donated cache buffers "
                f"{self.max_recoveries + 1} times); build a new "
                "ContinuousBatchingEngine"
            )

    # -- request intake ------------------------------------------------------
    def validate_request(self, prompt_ids: Any, max_new_tokens: int = 32) -> np.ndarray:
        """Validate one prompt against the engine's static limits WITHOUT
        queueing anything; returns the normalized ``int32`` prompt array.
        Raises a typed :class:`IntakeError` subclass (all are ``ValueError``)
        so a serving front end can map each failure to a 4xx status. Failing
        loudly at intake beats wedging the scheduler."""
        prompt = np.asarray(
            prompt_ids._data if hasattr(prompt_ids, "_data") else prompt_ids,
            np.int32,
        ).reshape(-1)
        if prompt.size < 1:
            raise EmptyPromptError("empty prompt")
        if max_new_tokens < 1:
            raise InvalidTokenBudgetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size > self.prompt_bucket:
            raise PromptTooLongError(
                f"prompt ({prompt.size} tokens) exceeds prompt_bucket "
                f"({self.prompt_bucket}); configure a larger bucket"
            )
        if prompt.size + max_new_tokens > self.max_model_len:
            raise RequestTooLongError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_model_len ({self.max_model_len})"
            )
        worst = prompt.size + max_new_tokens - 1
        need = -(-worst // self.block_size)
        if need > self.num_blocks:
            # a request no eviction can ever make room for would sit at the
            # FIFO head forever and busy-loop run()
            raise RequestUnservableError(
                f"request needs {need} KV blocks worst-case "
                f"but the pool only has {self.num_blocks}"
            )
        return prompt

    def make_request(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> InferenceRequest:
        """Validate and construct (but do not queue) one request — the seam
        a serving layer uses to hold the handle it will stream from."""
        self._check_usable()
        prompt = self.validate_request(prompt_ids, max_new_tokens)
        return InferenceRequest(
            next(self._ids), prompt, max_new_tokens, eos_token_id,
            priority=priority, tenant=tenant, deadline=deadline,
        )

    def enqueue(self, req: InferenceRequest) -> int:
        """Queue a request built by :meth:`make_request`; returns its id.
        Intake stays open while the engine is mid-recovery — recovery is an
        engine-internal condition, not a caller error, so the request simply
        queues; only a PERMANENTLY failed engine (recovery exhausted)
        hard-rejects."""
        self._check_usable()
        self._waiting.append(req)
        self._update_pool_gauges()  # queue depth changed
        return req.req_id

    def add_request(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = 1,
        tenant: str = "default",
        deadline: Optional[float] = None,
    ) -> int:
        """Queue one prompt; returns the request id. Raises a typed
        :class:`IntakeError` on prompts that can never be served (see
        :meth:`validate_request`)."""
        return self.enqueue(
            self.make_request(
                prompt_ids, max_new_tokens, eos_token_id,
                priority=priority, tenant=tenant, deadline=deadline,
            )
        )

    def has_work(self) -> bool:
        return bool(self._waiting) or any(r is not None for r in self._slot_req)

    @property
    def broken(self) -> bool:
        """True once recovery is exhausted and the engine is PERMANENTLY
        failed (a transient, caller-retryable step failure does not set
        this — see :meth:`step`)."""
        return self._broken

    def queue_depth(self) -> int:
        """Requests waiting for a slot (what the queue-depth gauge exports)."""
        return len(self._waiting)

    def prefix_chain_hash(
        self, prompt_ids: Any, max_blocks: Optional[int] = None
    ) -> str:
        """Hex digest of the prompt's block-aligned prefix chain — the same
        rolling blake2b the prefix cache keys chain nodes by, so a router
        keying on this lands requests sharing a prefix on the replica whose
        cache already holds that prefix's KV. ``max_blocks`` caps the walk
        (see :func:`~paddle_tpu.inference.prefix_cache.chain_digest`)."""
        prompt = np.asarray(
            prompt_ids._data if hasattr(prompt_ids, "_data") else prompt_ids,
            np.int32,
        ).reshape(-1)
        return chain_digest(prompt, self.block_size, max_blocks).hex()

    def mark_failed(self, why: str = "externally marked failed") -> None:
        """Administrative seam: flip the engine to PERMANENTLY failed, as if
        recovery were exhausted — every later ``step()``/intake raises. The
        cluster layer's ``replica.kill`` fault site models a whole-process
        replica death through this (the host-side results in
        ``drain_finished()`` stay salvageable, mirroring the pump-death
        seam)."""
        self._broken = True
        self._flight.record("engine_marked_failed", why=str(why)[:200])

    def live_requests(self) -> List[InferenceRequest]:
        """Requests currently holding a slot (mid-decode), slot order."""
        return [r for r in self._slot_req if r is not None]

    def set_admission_policy(self, policy: AdmissionPolicy) -> None:
        """Swap the admission policy (takes effect at the next boundary)."""
        self._policy = policy

    def set_replica_scope(
        self,
        name: str,
        scope: Optional[Any] = None,
        flight: Optional[Any] = None,
    ) -> None:
        """Re-bind this engine's observability to a replica scope, resolved
        ONCE here: every ``engine_*``/``spec_decode_*``/``prefix_cache_*``/
        ``kv_tier_*`` series it records from now on carries a
        ``replica=name`` label (rolling up into the same process-global
        families), and flight events land in a per-replica child ring teed
        into the global black box. Called by the cluster layer at replica
        construction; the per-record cost is unchanged (the same one
        cached-bool read on the metrics-off path)."""
        if scope is None:
            scope = _obs.GLOBAL_METRICS.scope(replica=name)
        if flight is None:
            flight = _flight.GLOBAL_FLIGHT_RECORDER.child(replica=name)
        self.replica_name = str(name)
        self._metrics_scope = scope
        self._metrics = scope.bind_all(_engine_metrics())
        self._flight = flight
        if self._cache is not None:
            self._cache.set_replica_scope(scope, flight)
        if self._host_tier is not None:
            self._host_tier.set_replica_scope(scope)

    def cancel_request(
        self, req_id: int, reason: str = "cancelled"
    ) -> Optional[InferenceRequest]:
        """Targeted eviction: remove ``req_id`` wherever it lives. A queued
        request is dropped before its prefill ever runs; a mid-decode one is
        evicted from its slot with its KV blocks reclaimed immediately. The
        request (``finish_reason = reason``) is returned to THIS caller and
        will NOT also be delivered by step() — exactly-once holds with the
        cancel return value as the one delivery. Returns None when the id is
        unknown (already finished and delivered, or never queued)."""
        for req in self._waiting:
            if req.req_id == req_id:
                self._waiting.remove(req)
                req.finish_reason = reason
                req.finish_wall = time.perf_counter()
                self._flight.record(
                    "shed_queued", req_id=req.req_id, reason=reason
                )
                self._metrics["finished"].labels(reason=reason).inc()
                self._update_pool_gauges()
                return req
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.req_id == req_id:
                req.finish_reason = reason
                self._release(slot, req)
                return req
        return None

    # -- the compiled program (traces exactly ONCE per engine) ---------------
    def _param_arrays(self) -> List[Any]:
        # re-read each call: weight updates after construction are served
        # without retraces (same shapes/dtypes -> same compiled program).
        # Quantized projections contribute their per-output-channel scales
        # as EXTRA operands — the count is fixed per configuration, so the
        # ONE compiled step signature is unchanged.
        return [p._data for _, p in self._named] + [
            p._quant_scale for p in self._wq_params
        ]

    def _step_impl(
        self, param_arrays, caches, toks, tables, lens, q_lens, active,
        cow_src, cow_dst,
    ):
        """The ONE program: ``toks [S, C]`` ragged new tokens per slot
        (decode rows have one valid token, prefill chunks up to C);
        ``tables [S, MBS]``; ``lens`` tokens already cached per slot;
        ``q_lens`` valid new tokens; ``active`` the slot mask; ``cow_*`` the
        copy-on-write fork set (``dst == num_blocks``: no fork). Applies
        pending CoW forks, appends the ragged chunk KV, attends, and returns
        EVERY row's greedy argmax ``[S, C]`` — row ``j`` is the model's next
        token after the row-``j`` input, which is simultaneously the decode
        output (a plain slot reads row 0), the prompt-completion output (read
        at the last valid row), and the speculative verification surface (a
        drafted slot compares rows ``0..K-1`` against its draft left-to-
        right). Rows past ``q_lens`` are garbage and never read host-side."""
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.incubate.nn.functional import block_cache_cow_copy
        from paddle_tpu.nn.layer.layers import (
            bind_param_arrays,
            bind_quant_scales,
        )

        self.stats["step_traces"] += 1  # Python side: counts TRACES only
        n_named = len(self._named)
        weights, wq_scales = param_arrays[:n_named], param_arrays[n_named:]
        with bind_param_arrays(self._named, weights), bind_quant_scales(
            self._wq_params, wq_scales
        ):
            if self._quant_kv:
                # scale planes ride the same CoW fork set as their payload:
                # a forked block gets its source's scales in the same step
                forked = [
                    block_cache_cow_copy(
                        kc, vc, cow_src, cow_dst,
                        key_scale=ks, value_scale=vs,
                    )
                    for kc, vc, ks, vs in caches
                ]
                pkv = [
                    (
                        Tensor(kc), Tensor(vc), Tensor(tables), Tensor(lens),
                        Tensor(active), Tensor(q_lens),
                        Tensor(ks), Tensor(vs),
                    )
                    for kc, vc, ks, vs in forked
                ]
            else:
                forked = [
                    block_cache_cow_copy(kc, vc, cow_src, cow_dst)
                    for kc, vc in caches
                ]
                pkv = [
                    (
                        Tensor(kc), Tensor(vc), Tensor(tables), Tensor(lens),
                        Tensor(active), Tensor(q_lens),
                    )
                    for kc, vc in forked
                ]
            with paddle_tpu.no_grad():
                logits, new_pkv = self.model(
                    Tensor(toks),
                    past_key_values=pkv,
                    use_cache=True,
                    cache_position=Tensor(lens),
                )
            nxt = jnp.argmax(
                logits._data.astype(jnp.float32), axis=-1
            ).astype(jnp.int32)  # [S, C] per-row argmax
            if self._quant_kv:
                # quantized pasts are 8-tuples; scales come back at 6/7
                return nxt, [
                    (c[0]._data, c[1]._data, c[6]._data, c[7]._data)
                    for c in new_pkv
                ]
            return nxt, [(c[0]._data, c[1]._data) for c in new_pkv]

    # -- scheduling ----------------------------------------------------------
    def _blocks_needed(self, req: InferenceRequest) -> int:
        # tokens stored by the end: prompt + (max_new - 1) appended during
        # decode (the final generated token is emitted, never appended)
        worst = req.prompt.size + req.max_new_tokens - 1
        return -(-worst // self.block_size)

    def _can_fit(self, req: InferenceRequest) -> bool:
        need = self._blocks_needed(req)
        avail = self._unreserved_free()
        if self._cache is not None:
            matched, matched_evictable = self._cache.peek_cached_blocks(req.prompt)
            # matched blocks are mapped, not allocated — but a matched block
            # currently sitting in the evictable LRU was ALSO counted as
            # reclaimable headroom; pinning it consumes that headroom
            need -= matched
            avail -= matched_evictable
        return avail >= need

    def _alloc_private_block(self) -> int:
        """One request-private block, evicting zero-ref cached chains under
        pressure (the reservation math guarantees this succeeds for live
        slots' growth)."""
        if self._cache is not None:
            return self._cache.alloc_private_block()
        return self._mgr.acquire_block()

    def _shed_expired_queued(self, done: List[InferenceRequest]) -> None:
        """Shed queued requests whose deadline already passed — BEFORE any
        prefill is spent on them. They are delivered through the same step()
        return path as normal finishes, ``finish_reason == "deadline"``."""
        if not self._waiting:
            return
        now = time.perf_counter()
        expired = [r for r in self._waiting if r.expired(now)]
        for req in expired:
            self._waiting.remove(req)
            req.finish_reason = "deadline"
            req.finish_wall = now
            self._flight.record(
                "shed_queued", req_id=req.req_id, reason="deadline"
            )
            self._metrics["finished"].labels(reason="deadline").inc()
            done.append(req)
        if expired:
            self._update_pool_gauges()  # queue depth changed

    def _admit_waiting(self, done: List[InferenceRequest]) -> None:
        self._shed_expired_queued(done)
        while self._waiting:
            free_slots = [i for i, r in enumerate(self._slot_req) if r is None]
            if not free_slots:
                return
            req = self._policy.select(tuple(self._waiting), self._can_fit)
            if req is None:
                return
            # a buggy policy must fail loudly, not corrupt the worst-case
            # reservation invariant the pool depends on
            if req not in self._waiting:
                raise RuntimeError(
                    f"admission policy {type(self._policy).__name__} selected "
                    "a request that is not in the waiting queue"
                )
            if not self._can_fit(req):
                raise RuntimeError(
                    f"admission policy {type(self._policy).__name__} selected "
                    f"request {req.req_id} needing {self._blocks_needed(req)} "
                    f"blocks with only {self._unreserved_free()} unreserved"
                )
            self._waiting.remove(req)
            self._admit(req, free_slots[0])

    def _match_and_map(self, req: InferenceRequest, slot: int) -> None:
        """Map the longest cached prefix into ``slot``'s block table (host
        bookkeeping only — the slot's first chunk rides the NEXT unified
        step). A failing cache lookup (including an injected
        ``prefix_cache.match`` fault) degrades to a cold miss: the prompt is
        simply recomputed."""
        result = None
        if self._cache is not None:
            try:
                result = self._cache.match(req.prompt)
            except Exception as exc:  # noqa: BLE001 - lookup must never kill admission
                self._flight.record(
                    "prefix_match_failed", req_id=req.req_id,
                    error=f"{type(exc).__name__}: {exc}"[:120],
                )
        nodes = result.nodes if result is not None else []
        cached = result.cached_tokens if result is not None else 0
        cow = result.cow if result is not None else None
        self._nodes[slot] = list(nodes)
        self._blocks[slot] = [n.block for n in nodes]
        self._no_insert[slot] = False
        self._pending_cow[slot] = None
        if cow is not None:
            src_node, dst_block, partial = cow
            self._blocks[slot].append(dst_block)
            self._pending_cow[slot] = cow
            self._flight.record(
                "cow_fork", req_id=req.req_id, slot=slot,
                src_block=src_node.block, dst_block=dst_block,
                reused_tokens=partial,
            )
        if result is not None and (result.host_nodes or result.host_partial):
            cached += self._prefetch_spilled(slot, req, result)
        self._matched_blocks[slot] = len(self._nodes[slot])
        self._reserved[slot] = self._blocks_needed(req) - len(self._nodes[slot])
        self._ntok[slot] = cached
        req.cached_tokens = cached
        self.stats["prompt_tokens_reused"] += cached

    def _prefetch_spilled(
        self, slot: int, req: InferenceRequest, result: Any
    ) -> int:
        """Land a matched spilled chain back into the pool: reserve slots
        for every matched host block (full chain nodes + the divergent
        block's partial source) atomically, issue their asynchronous H2D
        copies into the per-layer pools, re-register the full blocks as
        device chain nodes, and gate the slot until the copies land. Returns
        the prompt tokens this reused (0 on ANY failure — an injected
        ``kv_tier.prefetch`` fault, allocation shortfall, or a dispatch
        error all degrade to recomputing the suffix, with the already-mapped
        device chain untouched and nothing allocated)."""
        host_nodes: List[HostNode] = list(result.host_nodes)
        host_partial: Optional[Tuple[HostNode, int]] = result.host_partial
        n_blocks = len(host_nodes) + (1 if host_partial is not None else 0)
        blocks: List[int] = []
        try:
            try:
                fault_point("kv_tier.prefetch")
                blocks = self._cache.alloc_landing_blocks(n_blocks)
                copies = list(host_nodes)
                if host_partial is not None:
                    copies.append(host_partial[0])
                marker = None
                hd = self._hd
                for hn, blk in zip(copies, blocks):
                    dst = jnp.asarray(np.int32(blk))
                    for li in range(self._num_layers):
                        if self._quant_kv:
                            # packed host block [2, KVH, BS, D+4] int8: split
                            # the payload from the 4 trailing scale bytes and
                            # land all four planes in one fold program
                            kc, vc, ks, vs = self._caches[li]
                            kv = hn.kv[li]
                            hks = np.ascontiguousarray(
                                kv[0, ..., hd:]
                            ).view(np.float32)[..., 0]
                            hvs = np.ascontiguousarray(
                                kv[1, ..., hd:]
                            ).view(np.float32)[..., 0]
                            kc, vc, ks, vs, marker = self._fold_fn(
                                kc, vc, ks, vs, dst,
                                jnp.asarray(kv[0, ..., :hd]),
                                jnp.asarray(kv[1, ..., :hd]),
                                jnp.asarray(hks), jnp.asarray(hvs),
                            )
                            self._caches[li] = (kc, vc, ks, vs)
                        else:
                            kc, vc = self._caches[li]
                            kc, vc, marker = self._fold_fn(
                                kc, vc, dst,
                                jnp.asarray(hn.kv[li, 0]),
                                jnp.asarray(hn.kv[li, 1]),
                            )
                            self._caches[li] = (kc, vc)
            except Exception as exc:  # noqa: BLE001 - degrade to recompute
                for blk in blocks:  # reserved but never mapped: hand back
                    self._mgr.decref(blk)
                self._flight.record(
                    "kv_prefetch_failed", req_id=req.req_id, slot=slot,
                    blocks=n_blocks,
                    error=f"{type(exc).__name__}: {exc}"[:120],
                )
                return 0
        finally:
            # pins exist only to bridge match -> copy-issue: once the copies
            # are in the dispatch queue (jax holds its own reference to the
            # host planes) or the prefetch is abandoned, the LRU may move
            self._cache.release_host_pins(result)
        # commit phase (cannot fail): map the landed blocks into the slot's
        # table and re-register the full blocks as device chain nodes so
        # later admissions share them without another prefetch. A key that
        # re-registered concurrently keeps our copy private (same layout as
        # the in-flight insert race).
        tokens = 0
        parent = self._nodes[slot][-1] if self._nodes[slot] else None
        registering = True
        for i, hn in enumerate(host_nodes):
            blk = blocks[i]
            self._blocks[slot].append(blk)
            tokens += self.block_size
            if registering:
                node = self._cache.insert(parent, hn.tokens(), blk)
                if node is None:
                    registering = False
                else:
                    self._nodes[slot].append(node)
                    parent = node
        if host_partial is not None:
            # the divergent block's leading run, prefetched instead of
            # copy-on-write forked: the whole block landed, the request
            # overwrites it from the divergence point on — private forever
            # (its eventual content differs from the spilled source)
            self._blocks[slot].append(blocks[-1])
            tokens += host_partial[1]
        self._host_tier.mark_prefetched(n_blocks)
        self._cache.record_host_reuse(tokens)
        self._prefetch_wait[slot] = (marker, n_blocks, tokens)
        self._flight.record(
            "kv_prefetch", req_id=req.req_id, slot=slot, blocks=n_blocks,
            tokens=tokens,
        )
        return tokens

    def _poll_prefetch_gates(self, wait: bool = False) -> None:
        """Clear the prefetch gate of every slot whose H2D copies have
        landed (``wait=True`` blocks on them — the escape hatch when gated
        slots are the only work, so the engine can never stall on its own
        gate)."""
        for i in range(self.max_slots):
            pending = self._prefetch_wait[i]
            if pending is None:
                continue
            marker = pending[0]
            if wait:
                jax.block_until_ready(marker)
                ready = True
            else:
                ready = bool(getattr(marker, "is_ready", lambda: True)())
            if ready:
                self._prefetch_wait[i] = None

    def _admit(self, req: InferenceRequest, slot: int) -> None:
        # the prefill fault site moved host-side with chunked prefill: it
        # models an admission-time failure (match/map), and — like a real
        # dispatch loss — an InjectedFault here takes the recovery path
        try:
            fault_point("engine.prefill")
            self._match_and_map(req, slot)
        except BaseException:
            # broad on purpose: whatever kills admission (injected fault,
            # MemoryError from the CoW alloc, operator interrupt), the
            # partially-mapped slot must be unwound so pool accounting is
            # exactly as before this admit; step()'s retry loop classifies
            self._rollback_admit(slot)
            self._waiting.appendleft(req)  # keeps FIFO order for a retry
            raise
        req.prefill_start = time.perf_counter()
        self._slot_req[slot] = req
        self._last_tok[slot] = 0
        self.stats["admitted"] += 1
        self._flight.record(
            "admit", req_id=req.req_id, slot=slot,
            prompt_len=int(req.prompt.size), cached_tokens=int(req.cached_tokens),
            queue_depth=len(self._waiting),
        )
        self._metrics["admitted"].inc()
        self._update_pool_gauges()

    def _rollback_admit(self, slot: int) -> None:
        """Undo a partially-mapped admission so a failure leaves the pool
        accounting exactly as before."""
        if self._cache is not None:
            if self._pending_cow[slot] is not None:
                src_node, dst_block, _ = self._pending_cow[slot]
                self._cache.release_cow_source(src_node)
                self._mgr.decref(dst_block)
                if self._blocks[slot] and self._blocks[slot][-1] == dst_block:
                    self._blocks[slot].pop()
            if self._nodes[slot]:
                self._cache.release(self._nodes[slot])
        # prefetched blocks that stayed private (insert race / the partial
        # arm) sit past the node prefix: hand them back too
        for blk in self._blocks[slot][len(self._nodes[slot]):]:
            self._mgr.decref(blk)
        self._nodes[slot] = []
        self._blocks[slot] = []
        self._matched_blocks[slot] = 0
        self._pending_cow[slot] = None
        self._prefetch_wait[slot] = None
        self._reserved[slot] = 0
        self._ntok[slot] = 0

    def _release(self, slot: int, req: InferenceRequest) -> None:
        # finished requests are handed back ONLY through step()'s return
        # value (run() accumulates them); the engine keeps no reference, so
        # a long-running step()-driven server never grows host memory
        # skip chain registration under a pending CoW fork: its device copy
        # never executed, so that block's content is garbage and must not be
        # hashed into the cache
        had_pending_cow = self._pending_cow[slot] is not None
        if self._cache is not None and had_pending_cow:
            # cancelled before its first step: unpin the CoW source
            self._cache.release_cow_source(self._pending_cow[slot][0])
        self._pending_cow[slot] = None
        if not had_pending_cow:
            self._register_finished_chain(slot, req)
        nodes = self._nodes[slot]
        if self._cache is not None and nodes:
            self._cache.release(nodes)
        for blk in self._blocks[slot][len(nodes):]:
            self._mgr.decref(blk)  # private blocks free immediately
        self._nodes[slot] = []
        self._blocks[slot] = []
        self._matched_blocks[slot] = 0
        self._no_insert[slot] = False
        # a gate left by a released/cancelled slot is dropped, not waited
        # on: the in-flight copies still execute in dispatch order, and any
        # reuse of their target blocks happens in LATER dispatches that
        # consume the folded arrays — ordering keeps them safe
        self._prefetch_wait[slot] = None
        self._reserved[slot] = 0
        self._slot_req[slot] = None
        self._ntok[slot] = 0
        self._last_tok[slot] = 0
        req.finish_wall = time.perf_counter()
        self._flight.record(
            "evict", req_id=req.req_id, slot=slot,
            reason=req.finish_reason or "unknown",
            n_generated=len(req.generated),
        )
        self._metrics["evicted"].inc()
        self._metrics["finished"].labels(reason=req.finish_reason or "unknown").inc()
        self._update_pool_gauges()

    def step(self) -> List[InferenceRequest]:
        """One engine iteration: reclaim/admit, then one unified
        prefill/decode step over all active slots. Returns requests that
        finished during this step — the ONLY handback: the engine keeps no
        reference to finished requests (a step()-driven server never grows
        host memory), so a later run() will not re-deliver them.

        Failure policy: a dispatch failure that left the cache buffers
        intact (no donation consumed them) re-raises immediately with host
        state rolled back — the caller may simply retry. A failure that
        consumed the donated buffers (``_buffers_lost()``; an
        :class:`InjectedFault` from a fault plan models exactly this) runs
        :meth:`recover` and retries, up to ``max_recoveries`` times with
        exponential backoff, then marks the engine permanently failed and
        re-raises."""
        self._check_usable()
        attempt = 0
        while True:
            try:
                self._step_attempt()
                break
            except BaseException as exc:
                # broad on purpose: ANY dispatch failure must be classified
                # (recoverable buffers-lost vs caller-retryable) — except an
                # operator interrupt, which is never a recovery trigger and
                # must propagate NOW, not after sleep+recover+retry; if it
                # consumed donated buffers, the next step() call recovers
                if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                    raise
                # an injected dispatch fault models the donating-backend
                # failure mode (buffers consumed by the aborted dispatch),
                # so it takes the same recovery path on every backend
                recoverable = self._buffers_lost() or isinstance(exc, InjectedFault)
                if not recoverable or attempt >= self.max_recoveries:
                    self._broken = recoverable
                    if self._broken:
                        self._dump_black_box(exc)
                    raise
                attempt += 1
                time.sleep(self.recovery_backoff * (2 ** (attempt - 1)))
                try:
                    self.recover()
                except BaseException as rexc:
                    # a dispatch failure DURING recovery (device truly dead,
                    # injected or real) leaves half-rebuilt KV — permanent
                    self._broken = True
                    self._dump_black_box(rexc)
                    raise
        # deliver everything that finished during this (possibly retried)
        # step exactly once — including finishers from an attempt whose
        # dispatch later died
        return self.drain_finished()

    def _dump_black_box(self, exc: BaseException) -> None:
        """The engine just became PERMANENTLY failed: write the flight
        recorder's recent-event ring to disk so the postmortem has a
        timeline. safe_dump never raises — the original exception is what
        the caller must see."""
        self._flight.record(
            "engine_permanent_failure",
            error=f"{type(exc).__name__}: {exc}"[:200],
            live=sum(r is not None for r in self._slot_req),
            queued=len(self._waiting),
        )
        self._flight.safe_dump(
            "engine_permanent_failure",
            extra={
                "error": f"{type(exc).__name__}: {exc}"[:200],
                "stats": dict(self.stats),
                "pool": self.pool_stats(),
            },
        )

    def drain_finished(self) -> List[InferenceRequest]:
        """Hand back finished-but-undelivered requests. Normally step() is
        the only delivery path; this exists for the salvage case — a step
        whose delivery was preempted by an exception (including a PERMANENT
        engine failure) leaves complete results the host already holds, and
        they must be collectable rather than stranded. Usable on a broken
        engine; exactly-once still holds (the buffer is drained)."""
        out, self._pending_done = self._pending_done, []
        return out

    # -- the unified dispatch ------------------------------------------------
    def _dense_tables(self) -> np.ndarray:
        out = np.zeros((self.max_slots, self.max_blocks_per_seq), np.int32)
        for s, blocks in enumerate(self._blocks):
            if blocks:
                out[s, : len(blocks)] = blocks
        return out

    def _devprof_cost_thunk(
        self, toks, tables, q_lens, active, cow_src, cow_dst
    ) -> Callable[[], Any]:
        """Zero-arg thunk handing devprof the just-compiled step program's
        ``cost_analysis()``. It is an introspective AOT lowering — it re-runs
        the ``_step_impl`` Python trace and pays one extra XLA compile — so
        devprof only invokes it while ``FLAGS_devprof_sample_rate > 0``.
        The re-trace bumps ``stats["step_traces"]``; save/restore keeps the
        1-compile invariant (and the watchdog ledger it feeds) honest: this
        trace produces a throwaway executable, not a new step program.
        Lowered with the live committed arrays under the same shard context
        as the real call, so under tp the analyzed program carries the real
        GSPMD partitioning (and its inserted collectives)."""

        def thunk():
            traces_before = self.stats["step_traces"]
            try:
                tp_ctx = (
                    self._tp_ctx(self._tp_mesh)
                    if self._tp_mesh is not None
                    else contextlib.nullcontext()
                )
                with tp_ctx:
                    lowered = self._step_fn.lower(
                        self._param_arrays(), self._caches, jnp.asarray(toks),
                        jnp.asarray(tables), jnp.asarray(self._ntok.copy()),
                        jnp.asarray(q_lens), jnp.asarray(active),
                        jnp.asarray(cow_src), jnp.asarray(cow_dst),
                    )
                return lowered.compile().cost_analysis()
            finally:
                self.stats["step_traces"] = traces_before

        return thunk

    def _dispatch(
        self,
        toks: np.ndarray,  # [S, C]
        q_lens: np.ndarray,  # [S]
        active: np.ndarray,  # [S] bool
    ) -> np.ndarray:
        """Run ONE unified step over the given ragged rows: grow block
        tables for the new tokens, fold in pending CoW forks, dispatch, sync,
        then advance ``_ntok`` and register freshly completed full prompt
        blocks with the prefix cache. Host token bookkeeping (emission,
        finish checks) is the caller's. On failure every block allocated for
        this step is returned, so repeated failed steps cannot drift the
        reservation invariant."""
        appended: List[Tuple[int, int]] = []  # (slot, block) rollback list
        active_slots = [i for i in range(self.max_slots) if active[i]]
        cow_src = np.zeros((self.max_slots,), np.int32)
        cow_dst = np.full((self.max_slots,), self.num_blocks, np.int32)
        try:
            for i in active_slots:
                need_tokens = int(self._ntok[i]) + int(q_lens[i])
                while len(self._blocks[i]) * self.block_size < need_tokens:
                    blk = self._alloc_private_block()
                    self._blocks[i].append(blk)
                    appended.append((i, blk))
                pending = self._pending_cow[i]
                if pending is not None:
                    cow_src[i] = pending[0].block
                    cow_dst[i] = pending[1]
            tables = self._dense_tables()
            fault_point("engine.decode")
            traces_before = self.stats["step_traces"]
            # arm the tp shard group for the (first-call / recovery) trace:
            # the paged-attention functional reads it at TRACE time to wrap
            # the Pallas kernel in shard_map over the head shard; executions
            # of the already-compiled program never re-enter Python
            tp_ctx = (
                self._tp_ctx(self._tp_mesh)
                if self._tp_mesh is not None
                else contextlib.nullcontext()
            )
            marks = self._devprof_marks  # non-None only on a sampled step
            if marks is not None:
                marks["call_s"] = time.perf_counter()
            with tp_ctx:
                nxt, self._caches = self._step_fn(
                    self._param_arrays(), self._caches, jnp.asarray(toks),
                    jnp.asarray(tables), jnp.asarray(self._ntok.copy()),
                    jnp.asarray(q_lens), jnp.asarray(active),
                    jnp.asarray(cow_src), jnp.asarray(cow_dst),
                )
            if marks is not None:
                marks["ret_s"] = time.perf_counter()
        except BaseException:
            # roll the per-step allocations back so a transient failure
            # leaves the allocator in lockstep with _ntok (retried steps
            # neither leak blocks nor break the reservation invariant);
            # pending CoW forks stay pending — a retry re-copies
            for slot, blk in appended:
                self._blocks[slot].remove(blk)
                self._mgr.decref(blk)
            raise
        if self.stats["step_traces"] > traces_before:
            # recorded HERE, after the jit call returned: a trace that died
            # mid-body bumped the stats counter but produced no program, and
            # the watchdog ledger must only count compiles that exist
            GLOBAL_WATCHDOG.record_compile(
                "ContinuousBatchingEngine.step",
                signature=f"toks[{self.max_slots},{self.prefill_chunk}]"
                + (f"|tp{self.tp}" if self.tp > 1 else ""),
                cause=CAUSE_FIRST_CALL
                if not self._step_recorded
                else CAUSE_NEW_SHAPE_DTYPE,
                cost_thunk=self._devprof_cost_thunk(
                    toks, tables, q_lens, active, cow_src, cow_dst
                ),
                cost_hints=self._devprof_hints,
            )
            self._step_recorded = True
        nxt = np.asarray(nxt)  # device sync: the step's tokens are real here
        if marks is not None:
            marks["sync_s"] = time.perf_counter()
        if self._quant_kv and _obs.metrics_enabled():
            # host-side attribution of the step's quantized-plane traffic:
            # every new token was quantized on write, every active slot's
            # block walk dequantized on read (one cached-bool check + two
            # counter adds per STEP — nothing per token)
            self._metrics["kv_quant"].labels(op="quant").inc(
                float(sum(int(q_lens[i]) for i in active_slots))
            )
            self._metrics["kv_quant"].labels(op="dequant").inc(
                float(len(active_slots))
            )
        for i in active_slots:
            pending = self._pending_cow[i]
            if pending is not None:
                # the fork's device copy has executed — unpin the source
                if self._cache is not None:
                    self._cache.release_cow_source(pending[0])
                self._pending_cow[i] = None
            self._ntok[i] += int(q_lens[i])
            self._extend_chain(i)
        return nxt

    def _register_finished_chain(self, slot: int, req: InferenceRequest) -> None:
        """At request FINISH, extend the slot's chain with its full blocks
        of COMMITTED generated tokens, so a multi-turn conversation's second
        turn (prompt = first turn's prompt + reply + new text) maps its
        first turn's KV instead of recomputing it. Rewind-safe by
        construction: only tokens the block table still covers are hashed —
        ``_ntok`` is the committed length, and everything a speculative
        rewind discarded is already gone by commit time, long before this
        runs. Reuses the in-flight insert machinery, so the release that
        follows drops only this request's reference and the chain stays
        warm in the LRU for the next turn's match."""
        if self._cache is None or self._no_insert[slot]:
            return
        valid = int(self._ntok[slot])
        full = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]
        )
        bs = self.block_size
        while True:
            idx = len(self._nodes[slot])
            end = (idx + 1) * bs
            # cap at the emitted stream too: an eos inside an accepted draft
            # leaves KV past the last emitted token — valid content, but not
            # part of any prompt a next turn would replay, so never hashed
            if end > valid or end > full.size or idx >= len(self._blocks[slot]):
                return
            parent = self._nodes[slot][-1] if self._nodes[slot] else None
            node = self._cache.insert(
                parent, full[idx * bs : end], self._blocks[slot][idx]
            )
            if node is None:
                return  # identical chain already cached; keep ours private
            self._nodes[slot].append(node)
            if end > req.prompt.size:
                self.stats["gen_blocks_registered"] += 1

    def _extend_chain(self, slot: int) -> None:
        """Register this slot's freshly COMPLETED full prompt blocks as
        chain nodes (in-flight insertion: later admissions share them the
        moment they are computed). Blocks containing any generated token
        stay private until the request finishes — a live tail can still be
        rewound by speculation, so only :meth:`_register_finished_chain`
        (which runs after the last commit) ever hashes generated content."""
        if self._cache is None or self._no_insert[slot]:
            return
        req = self._slot_req[slot]
        if req is None:
            return
        plen = req.prompt.size
        bs = self.block_size
        while True:
            idx = len(self._nodes[slot])
            end = (idx + 1) * bs
            if end > plen or end > int(self._ntok[slot]):
                return
            if idx >= len(self._blocks[slot]):
                return
            parent = self._nodes[slot][-1] if self._nodes[slot] else None
            node = self._cache.insert(
                parent, req.prompt[idx * bs : end], self._blocks[slot][idx]
            )
            if node is None:
                # another request registered the same chain block first
                # (same-boundary concurrent compute); keep ours private and
                # stop extending so node/block alignment stays simple
                self._no_insert[slot] = True
                return
            self._nodes[slot].append(node)

    # -- speculative decoding ------------------------------------------------
    def _propose_draft(self, req: InferenceRequest) -> np.ndarray:
        """Host-side draft for one decode slot. The width is capped THREE
        ways: the chunk can carry ``prefill_chunk - 1`` draft rows next to
        the real last token; the request's remaining token budget bounds it
        at ``max_new - generated - 1`` (so even a fully accepted draft plus
        its bonus token lands exactly on the budget — KV never grows past
        the slot's worst-case admission reservation); and the drafter itself
        returns only what the history supports (possibly nothing — the slot
        then stays a plain decode row at zero cost)."""
        budget = req.max_new_tokens - len(req.generated) - 1
        k_max = min(self._spec_k, budget)
        if k_max < 1:
            return np.empty((0,), np.int32)
        # hand the drafter only the tail it can actually read (its search
        # window plus the n-gram lookback) — proposals are identical, but a
        # long generation no longer re-copies its whole O(context) history
        # per slot per step
        d = self._drafter
        need = d.window + d.ngram_max + 1
        gen = req.generated
        if len(gen) >= need:
            ctx = np.asarray(gen[-need:], np.int32)
        else:
            # clamp at 0: a start index going negative would wrap and slice
            # a short suffix instead of the whole prompt
            start = max(req.prompt.size - (need - len(gen)), 0)
            ctx = np.concatenate(
                [req.prompt[start:], np.asarray(gen, np.int32)]
            )
        return d.propose(ctx, k_max)

    def _commit_speculation(
        self,
        slot: int,
        req: InferenceRequest,
        row_argmax: np.ndarray,  # [C] this slot's per-row argmax
        draft: np.ndarray,
    ) -> None:
        """Verify and commit one slot's draft against the step that scored
        it. Accepted tokens commit in bulk — their KV was written by the
        very dispatch that verified them — followed by the bonus token (the
        argmax after the last accepted draft, which plain decode would have
        produced next anyway); the first rejection rewinds the block table
        to the committed length. An injected ``spec.verify`` fault degrades
        the slot to plain decode for this step: accept nothing, keep row
        0's argmax (computed from committed history only — its value does
        not depend on the draft), rewind the drafted rows. No tokens are
        lost and no accounting drifts on that path."""
        k = int(draft.size)
        base = int(self._ntok[slot]) - (1 + k)  # committed before this step
        try:
            fault_point("spec.verify")
            accepted = count_accepted(row_argmax, draft)
        except Exception as exc:  # noqa: BLE001 - degrade, never corrupt
            self._flight.record(
                "spec_verify_degraded", req_id=req.req_id, slot=slot,
                error=f"{type(exc).__name__}: {exc}"[:120],
            )
            accepted = 0
        # rewind FIRST: _ntok / block-table truth must equal the committed
        # length before any finish path below releases the slot
        self._rewind_slot(slot, req, base + 1 + accepted, drafted=k,
                          accepted=accepted)
        emit = [int(draft[j]) for j in range(accepted)]
        emit.append(int(row_argmax[accepted]))  # the bonus token
        for tok in emit:
            req.generated.append(tok)
            self._last_tok[slot] = tok
            if req.eos_token_id is not None and tok == req.eos_token_id:
                req.finish_reason = "stop"
                break
            if len(req.generated) >= req.max_new_tokens:
                req.finish_reason = "length"
                break
        self.stats["spec_steps"] += 1
        self.stats["spec_drafted"] += k
        self.stats["spec_accepted"] += accepted
        self.stats["spec_rejected"] += k - accepted
        m = self._metrics
        m["spec_drafted"].inc(k)
        m["spec_accepted"].inc(accepted)
        m["spec_rejected"].inc(k - accepted)
        m["spec_accept_rate"].observe(accepted / k)
        if req.finished:
            self._release(slot, req)
            self._pending_done.append(req)

    def _rewind_slot(
        self, slot: int, req: InferenceRequest, target_ntok: int,
        drafted: int, accepted: int,
    ) -> None:
        """Block-table rewind: discard the KV written past ``target_ntok``
        by truncating the slot's table through the refcounted pool. Chain-
        owned blocks are never touched — drafts only ever write past the
        prompt, into request-private blocks — and the stale KV left in the
        retained partial block is unreadable (every later row's attention is
        limited to positions below the committed length) and is overwritten
        in place as the sequence advances."""
        self._ntok[slot] = target_ntok
        keep = max(-(-target_ntok // self.block_size), len(self._nodes[slot]))
        freed = 0
        while len(self._blocks[slot]) > keep:
            self._mgr.decref(self._blocks[slot].pop())
            freed += 1
        if accepted < drafted:
            self._flight.record(
                "spec_rewind", req_id=req.req_id, slot=slot, drafted=drafted,
                accepted=accepted, rejected=drafted - accepted,
                blocks_freed=freed,
            )

    def spec_decode_stats(self) -> Dict[str, Any]:
        """Acceptance-rate view for /healthz, the serving goodput record and
        bench (host counters — valid with metrics off)."""
        drafted = self.stats["spec_drafted"]
        return {
            "enabled": self._use_spec,
            "drafted_tokens": drafted,
            "accepted_tokens": self.stats["spec_accepted"],
            "rejected_tokens": self.stats["spec_rejected"],
            "acceptance_rate": (
                self.stats["spec_accepted"] / drafted if drafted else 0.0
            ),
            "speculative_steps": self.stats["spec_steps"],
        }

    def _step_attempt(self) -> None:
        """One admit+dispatch pass; finished requests land in
        ``_pending_done`` (never lost to an exception mid-attempt)."""
        # mid-decode deadline expiry FIRST: evict before paying for another
        # step of this slot's compute, so the freed slot/blocks are available
        # to the admit pass below in the same boundary
        now = time.perf_counter()
        for i, req in enumerate(self._slot_req):
            if req is not None and req.expired(now):
                req.finish_reason = "deadline"
                self._release(i, req)
                self._pending_done.append(req)
        self._admit_waiting(self._pending_done)
        # prefetch gating: a slot whose host-tier blocks are still in H2D
        # flight contributes no rows this step — its chunks only ride the
        # mixed step once the copies have landed, and the copies overlap
        # with the other slots' compute meanwhile. When gated slots are the
        # ONLY live work there is nothing to overlap with: wait them out so
        # the engine can never stall on its own gate.
        self._poll_prefetch_gates()
        active_slots = [
            i for i, r in enumerate(self._slot_req)
            if r is not None and self._prefetch_wait[i] is None
        ]
        if not active_slots:
            if any(w is not None for w in self._prefetch_wait):
                self._poll_prefetch_gates(wait=True)
                active_slots = [
                    i for i, r in enumerate(self._slot_req) if r is not None
                ]
            if not active_slots:
                return
        C = self.prefill_chunk
        toks = np.zeros((self.max_slots, C), np.int32)
        q_lens = np.zeros((self.max_slots,), np.int32)
        active = np.zeros((self.max_slots,), bool)
        prefill_tokens = 0
        # slot -> draft packed into this attempt's chunk rows; LOCAL on
        # purpose: a failed dispatch retries through a fresh _step_attempt
        # that re-proposes, so no speculative state can ever go stale
        drafts: Dict[int, np.ndarray] = {}
        for i in active_slots:
            req = self._slot_req[i]
            plen = req.prompt.size
            cur = int(self._ntok[i])
            active[i] = True
            if cur < plen:  # chunked prefill row(s)
                n = min(C, plen - cur)
                toks[i, :n] = req.prompt[cur : cur + n]
                q_lens[i] = n
                prefill_tokens += n
            else:  # decode row, with the draft riding as extra chunk rows
                toks[i, 0] = self._last_tok[i]
                q_lens[i] = 1
                if self._drafter is not None and self._pending_cow[i] is None:
                    draft = self._propose_draft(req)
                    if draft.size:
                        k = int(draft.size)
                        toks[i, 1 : 1 + k] = draft
                        q_lens[i] = 1 + k
                        drafts[i] = draft
        # devprof sampling decision: one cached-bool read at rate 0 (the
        # stride counter only advances while the flag is on, and the stride
        # is deterministic — no RNG draw, seeded runs stay byte-identical)
        dp_sampled = self._devprof_gate.should_sample()
        comm_ops: Dict[str, float] = {}
        if dp_sampled:
            self._devprof_marks = {}
            _devprof.begin_comm_window()
        t0 = time.perf_counter()
        try:
            nxt = self._dispatch(toks, q_lens, active)
        except BaseException:
            # re-raised below: only dropping the armed marks dict so a later
            # non-sampled step's _dispatch can't write into stale state — a
            # failed sampled step records nothing
            self._devprof_marks = None
            raise
        finally:
            if dp_sampled:
                comm_ops = _devprof.end_comm_window()
        self.stats["steps"] += 1
        self.stats["prompt_tokens_computed"] += prefill_tokens
        if prefill_tokens:
            self._metrics["prefill_tokens"].inc(prefill_tokens)
        t1 = time.perf_counter()
        self._metrics["step"].observe(t1 - t0)
        if dp_sampled:
            marks, self._devprof_marks = self._devprof_marks or {}, None
            if {"call_s", "ret_s", "sync_s"} <= marks.keys():
                _devprof.record_step_profile(
                    "ContinuousBatchingEngine.step",
                    f"toks[{self.max_slots},{self.prefill_chunk}]"
                    + (f"|tp{self.tp}" if self.tp > 1 else ""),
                    t0, marks["call_s"], marks["ret_s"], marks["sync_s"],
                    comm_ops=comm_ops,
                    n_active=len(active_slots),
                    step=self.stats["steps"],
                    timeline=self._devprof_timeline,
                    flight=self._flight,
                )
        if _tracing.tracing_enabled():
            # per-request decode time in a continuous batch is a SHARE of
            # the batched step it rode; accumulate the even split on every
            # active request, and emit one batch-step span (annotated with
            # slot membership) when any rider is sampled
            share = (t1 - t0) / len(active_slots)
            membership: Dict[str, int] = {}
            any_sampled = False
            for i in active_slots:
                req = self._slot_req[i]
                req.decode_steps += 1
                req.decode_share_s += share
                membership[str(i)] = req.req_id
                if req.trace is not None and req.trace.sampled:
                    any_sampled = True
            if any_sampled:
                _tracing.GLOBAL_TRACER.add_span(
                    "engine.decode_step", start_s=t0, end_s=t1,
                    attrs={
                        "slot_req_ids": membership,
                        "n_active": len(active_slots),
                        "share_s": round(share, 9),
                    },
                )
        for i in active_slots:
            req = self._slot_req[i]
            if int(self._ntok[i]) < req.prompt.size:
                continue  # prompt not fully prefilled yet: no emission
            if i in drafts:
                self._commit_speculation(i, req, nxt[i], drafts[i])
                continue
            tok = int(nxt[i, max(int(q_lens[i]) - 1, 0)])  # last valid row
            if not req.generated:
                # the prompt just completed: this is the request's FIRST
                # token (TTFT ends here, not at admission)
                req.admit_time = time.perf_counter()
                self._metrics["ttft"].observe(req.admit_time - req.arrival_time)
            req.generated.append(tok)
            self._last_tok[i] = tok
            if req.eos_token_id is not None and tok == req.eos_token_id:
                req.finish_reason = "stop"
            elif len(req.generated) >= req.max_new_tokens:
                req.finish_reason = "length"
            if req.finished:
                self._release(i, req)
                self._pending_done.append(req)
        self._update_pool_gauges()  # step advanced every active slot

    def recover(self) -> None:
        """Rebuild device KV state after a dispatch failure consumed the
        donated cache buffers: reallocate the per-layer pools, reset the
        block allocator AND the prefix cache (its chain nodes point at lost
        KV), then re-prefill and replay every live slot from host-side truth
        (``InferenceRequest`` holds the prompt and every token generated so
        far). Request ids, emitted tokens, the waiting queue and pending
        finished deliveries are all preserved. Slots are re-prefilled ONE AT
        A TIME so slots sharing a prefix re-share it through the fresh cache
        (recovery can never need more blocks than the original admissions).

        The rebuilt buffers have identical shapes/dtypes, so the compiled
        program is reused — a recovery must not add compiles (the recompile
        watchdog still reports exactly 1 for this engine)."""
        from paddle_tpu.incubate.nn.functional import BlockKVCache

        live = [(i, req) for i, req in enumerate(self._slot_req) if req is not None]
        # chunked prefill means a live slot may be MID-PROMPT (no token
        # emitted yet): capture its progress before the reset so the replay
        # restores exactly the prefilled span, not the whole prompt
        prior_prefill = {
            i: int(min(self._ntok[i], req.prompt.size)) for i, req in live
        }
        t_recover = time.perf_counter()
        self._flight.record(
            "recovery", live=len(live), queued=len(self._waiting),
            recoveries=self.stats["recoveries"] + 1,
        )
        # identical shapes/dtypes/shardings (tp pools come back committed on
        # the same mesh partition) -> the compiled program is reused
        self._caches = [self._new_cache_pair() for _ in range(self._num_layers)]
        self._mgr = BlockKVCache(
            self.num_blocks, self.block_size, self._kvh, self._hd,
            self.max_blocks_per_seq, dtype=self._cache_dtype,
        )
        self._cache = self._new_prefix_cache()
        for i in range(self.max_slots):
            self._blocks[i] = []
            self._nodes[i] = []
            self._no_insert[i] = False
            self._pending_cow[i] = None
            # drop the in-flight prefetch set: its markers reference the
            # lost buffers. The HOST TIER ITSELF survives (host RAM was not
            # consumed) — it is part of the host truth this rebuild draws
            # from, so replayed prompts matching spilled chains prefetch
            # them into the fresh pools instead of recomputing.
            self._prefetch_wait[i] = None
        self._matched_blocks[:] = 0
        self._ntok[:] = 0
        self._last_tok[:] = 0
        self._reserved[:] = 0
        self.stats["recoveries"] += 1
        self._metrics["recoveries"].inc()

        # phase 1: re-prefill each live slot's prompt through the SAME
        # unified signature (chunked; a retrace here would be a bug and is
        # recorded so the 1-compile invariant test catches it); one slot at
        # a time so the fresh prefix cache re-deduplicates shared prefixes
        C = self.prefill_chunk
        for slot, req in live:
            self._match_and_map(req, slot)
            plen = req.prompt.size
            # a slot that never emitted replays only its prior prefill span
            # (the normal step flow finishes the prompt afterwards); a
            # decode-phase slot replays the whole prompt. The fresh cache may
            # map MORE than the prior span — cached KV is real content.
            target = plen if req.generated else prior_prefill[slot]
            while int(self._ntok[slot]) < target:
                toks = np.zeros((self.max_slots, C), np.int32)
                q_lens = np.zeros((self.max_slots,), np.int32)
                active = np.zeros((self.max_slots,), bool)
                cur = int(self._ntok[slot])
                n = min(C, target - cur)
                toks[slot, :n] = req.prompt[cur : cur + n]
                q_lens[slot] = n
                active[slot] = True
                self._dispatch(toks, q_lens, active)
            # the re-emitted first token is identical by determinism; host
            # truth is authoritative either way (the request already holds it)
            if req.generated:
                self._last_tok[slot] = req.generated[0]
            self._metrics["replayed"].inc()

        # phase 2: lockstep replay of already-generated tokens (one decode
        # row per catching-up slot per dispatch) — the KV append is the
        # effect we need; the re-emitted next tokens are discarded in favor
        # of the recorded ones
        max_replay = max((len(req.generated) - 1 for _, req in live), default=0)
        for r in range(max_replay):
            replay_slots = [i for i, req in live if len(req.generated) - 1 > r]
            toks = np.zeros((self.max_slots, C), np.int32)
            q_lens = np.zeros((self.max_slots,), np.int32)
            active = np.zeros((self.max_slots,), bool)
            for i in replay_slots:
                toks[i, 0] = self._last_tok[i]
                q_lens[i] = 1
                active[i] = True
            self._dispatch(toks, q_lens, active)
            for i in replay_slots:
                req = self._slot_req[i]
                self._last_tok[i] = req.generated[r + 1]
        if _tracing.tracing_enabled():
            _tracing.GLOBAL_TRACER.add_span(
                "engine.recover", start_s=t_recover, end_s=time.perf_counter(),
                attrs={"replayed_slots": len(live), "replay_depth": max_replay},
            )
        self._update_pool_gauges()

    def run(self) -> Dict[int, InferenceRequest]:
        """Drain the queue; returns {req_id: request} for everything that
        finished DURING this call (results from earlier direct step() calls
        were already returned by those calls)."""
        out: Dict[int, InferenceRequest] = {}
        while self.has_work():
            for req in self.step():
                out[req.req_id] = req
        return out
