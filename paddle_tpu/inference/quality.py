"""Quantization quality gate: measured accuracy delta of the quantized
serving configuration against the bf16 baseline on a seeded workload.

The quality claim a quantized deployment makes ("int8 KV + weight-only int8
serves the same tokens") is an EMPIRICAL one, so it is measured, not
asserted from algebra: the same seeded request stream runs through a bf16
engine and a quantized engine, and the delta is

- **greedy token-match rate** — the fraction of generated tokens identical
  to the bf16 engine's, end to end through the paged KV plane (append
  quant, block-walk dequant, CoW, spill/prefetch all included); and
- **max logit error** — the worst absolute logit difference of a direct
  full-forward on the same seeded prompts, isolating the weight-only int8
  projections from the KV path.

Both bench records (``bench.py``) and the tier-1 tolerance tests
(``tests/test_quantized_kv.py``) call this module, so the number the CI
gate enforces is the number the bench reports.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = ["greedy_token_match", "max_logit_error", "quality_delta"]


def _run_engine(
    build_model: Callable[[], Any],
    prompts: List[np.ndarray],
    max_new_tokens: int,
    engine_kwargs: Dict[str, Any],
) -> Dict[int, List[int]]:
    from paddle_tpu.inference import ContinuousBatchingEngine

    model = build_model()
    engine = ContinuousBatchingEngine(model, **engine_kwargs)
    for p in prompts:
        engine.add_request(np.asarray(p, np.int32), max_new_tokens=max_new_tokens)
    out = engine.run()
    return {rid: list(r.generated) for rid, r in out.items()}


def greedy_token_match(
    build_model: Callable[[], Any],
    prompts: List[np.ndarray],
    max_new_tokens: int,
    baseline_kwargs: Dict[str, Any],
    quant_kwargs: Dict[str, Any],
) -> Dict[str, Any]:
    """Run the SAME seeded workload through a baseline and a quantized
    engine (``build_model`` must re-seed, so both see identical weights) and
    return the positionwise greedy token-match rate. Sequences are compared
    id-by-id over the overlap; a quantized run that stops earlier/later
    counts every unpaired position as a mismatch — divergent early stops are
    a quality loss, not a bookkeeping artifact."""
    base = _run_engine(build_model, prompts, max_new_tokens, baseline_kwargs)
    quant = _run_engine(build_model, prompts, max_new_tokens, quant_kwargs)
    matched = total = 0
    for rid, ref in base.items():
        got = quant.get(rid, [])
        total += max(len(ref), len(got))
        matched += sum(a == b for a, b in zip(ref, got))
    return {
        "tokens_compared": total,
        "tokens_matched": matched,
        "token_match_rate": (matched / total) if total else 1.0,
    }


def max_logit_error(
    build_model: Callable[[], Any],
    prompts: List[np.ndarray],
    quantize: Optional[Callable[[Any], Any]] = None,
) -> float:
    """Worst absolute fp32 logit difference between a pristine model and a
    weight-quantized copy over a direct (cache-free) forward on the seeded
    prompts — the projection-error bound the KV path inherits. ``quantize``
    defaults to :func:`paddle_tpu.kernels.quant.quantize_module_weights`."""
    import paddle_tpu as paddle

    if quantize is None:
        from paddle_tpu.kernels.quant import quantize_module_weights as quantize

    ref_model = build_model()
    q_model = build_model()
    quantize(q_model)
    worst = 0.0
    for p in prompts:
        ids = paddle.to_tensor(np.asarray(p, np.int32)[None])
        ref = np.asarray(ref_model(ids).numpy(), np.float32)
        got = np.asarray(q_model(ids).numpy(), np.float32)
        worst = max(worst, float(np.max(np.abs(ref - got))))
    return worst


def quality_delta(
    build_model: Callable[[], Any],
    prompts: List[np.ndarray],
    max_new_tokens: int,
    engine_kwargs: Dict[str, Any],
    kv_cache_dtype: str = "int8",
    weight_only_int8: bool = True,
) -> Dict[str, Any]:
    """The full measured delta a bench record (or the tier-1 gate) carries:
    token-match rate through the engines, max logit error through a direct
    forward, and the effective KV bytes/token of both configurations (the
    reduction factor the tentpole promises)."""
    base_kwargs = dict(engine_kwargs)
    qkw = dict(
        engine_kwargs,
        kv_cache_dtype=kv_cache_dtype,
        weight_only_int8=weight_only_int8,
    )
    match = greedy_token_match(
        build_model, prompts, max_new_tokens, base_kwargs, qkw
    )
    out: Dict[str, Any] = dict(match)
    if weight_only_int8:
        out["max_logit_error"] = max_logit_error(build_model, prompts)
    # bytes/token from throwaway engines' accounting (no steps dispatched)
    from paddle_tpu.inference import ContinuousBatchingEngine

    bpt_base = ContinuousBatchingEngine(
        build_model(), **base_kwargs
    ).pool_stats()["bytes_per_token"]
    bpt_quant = ContinuousBatchingEngine(
        build_model(), **qkw
    ).pool_stats()["bytes_per_token"]
    out["kv_bytes_per_token_bf16"] = bpt_base
    out["kv_bytes_per_token_quant"] = bpt_quant
    out["kv_bytes_reduction"] = (
        bpt_base / bpt_quant if bpt_quant else float("inf")
    )
    return out
