"""Self-speculative decoding: n-gram / prompt-lookup drafting + greedy
verification for the continuous-batching engine.

Decode is latency-bound at one token per step per slot, but the engine's ONE
compiled signature — the ``[max_slots, prefill_chunk]`` mixed ragged step —
can already score K tokens for a slot as a "prompt chunk" with per-row causal
limits ("Ragged Paged Attention", PAPERS.md). That makes draft *verification*
architecturally free: a drafted slot packs ``[last_token, d1..dK]`` as a
(1+K)-row chunk into the SAME dispatch its plain-decode neighbours ride, the
step writes the drafted KV and returns every row's greedy argmax, and the
host compares argmax against draft left-to-right:

- row ``j``'s argmax is the model's next token after ``d_j`` (row 0: after
  ``last_token``), computed with exact causal attention over the cached
  history plus rows ``0..j`` — identical, bit for bit, to what plain decode
  would have produced one step at a time;
- the longest agreeing prefix is ACCEPTED in bulk: its KV was written by the
  very step that verified it, so a step that accepts ``a`` drafts commits
  ``a + 1`` tokens (the ``+1`` is the "bonus" argmax after the last accepted
  draft) for one dispatch;
- the first disagreement rewinds: the engine truncates the slot's block
  table back to the committed length (``BlockKVCache`` refcounts make this a
  host-side pop+decref), and the rejected rows' stale KV is never read —
  attention limits every later step to positions below the committed length.

The drafter here is the zero-extra-memory variant: **prompt lookup** over the
request's own prompt + generated history. Repetitive workloads (templated
prompts, code, multi-turn chats quoting earlier turns, the cyclic tails
greedy decode settles into) hand it long accepted runs; on incompressible
text it proposes nothing and the slot stays a plain decode row — speculation
can never make a step slower than the chunk it already dispatches. A small
draft *model* sharing the paged pool is the natural follow-on and slots into
the same propose/verify seam.

Config: ``FLAGS_spec_decode`` (master switch, read at engine construction,
per-engine ``spec_decode=`` override), ``FLAGS_spec_decode_ngram`` (longest
history n-gram matched; the drafter walks down to 1), and
``FLAGS_spec_decode_tokens`` (max draft tokens per slot per step, capped at
``prefill_chunk - 1`` so draft rows plus the mandatory last-token row fit
the compiled chunk).

Everything in this module is host-side numpy — drafting and verification are
data preparation for / bookkeeping after the one compiled step, never part
of any traced program.
"""

from __future__ import annotations

import numpy as np

__all__ = ["NGramDrafter", "count_accepted"]

_EMPTY = np.empty((0,), np.int32)


class NGramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most recent
    earlier occurrence of the context's trailing n-gram.

    ``ngram_max`` is the longest suffix n-gram tried (down to ``ngram_min``);
    longer matches predict the continuation more specifically and win over
    shorter ones, recency breaks ties. ``window``/``max_probes`` bound the
    per-step host cost (the drafter runs for every decode slot every step, so
    it must stay microseconds): only the last ``window`` context tokens are
    searched and only the ``max_probes`` most recent last-token anchors are
    scored — both deterministic truncations, chosen because repetition is
    local (the cycle the model just entered, the template instance being
    filled in right now). Stateless — one instance serves every slot."""

    def __init__(
        self,
        ngram_max: int = 3,
        ngram_min: int = 1,
        window: int = 128,
        max_probes: int = 32,
    ) -> None:
        self.ngram_max = max(int(ngram_max), 1)
        self.ngram_min = max(int(ngram_min), 1)
        self.window = max(int(window), 2)
        self.max_probes = max(int(max_probes), 1)
        if self.ngram_min > self.ngram_max:
            raise ValueError(
                f"ngram_min ({self.ngram_min}) must be <= ngram_max "
                f"({self.ngram_max})"
            )

    def propose(self, context: np.ndarray, max_tokens: int) -> np.ndarray:
        """Up to ``max_tokens`` draft tokens continuing ``context`` (the
        request's prompt + committed generated tokens), or an empty array
        when no history n-gram recurs. Anchored on the LAST token: every
        earlier occurrence of it is a candidate n-gram end; the candidate
        matching the most preceding tokens (capped at ``ngram_max - 1``)
        wins, most recent first."""
        context = np.asarray(context, np.int32).reshape(-1)
        L = context.size
        max_tokens = int(max_tokens)
        if max_tokens < 1 or L < 2:
            return _EMPTY
        lo = max(L - 1 - self.window, 0)
        anchors = np.nonzero(context[lo : L - 1] == context[L - 1])[0]
        if not anchors.size:
            return _EMPTY
        want = min(self.ngram_max, L) - 1  # preceding tokens a full match needs
        # score = (n-gram length, continuation available): a longer match
        # predicts better, and among equal matches one with a full
        # ``max_tokens`` continuation beats a more recent one that would
        # truncate the draft (in a tight cycle the most recent occurrence is
        # the suffix's immediate neighbour with almost nothing after it)
        best, best_j = (-1, -1), -1
        for j in anchors[::-1][: self.max_probes]:
            j = int(j) + lo
            avail = min(L - 1 - j, max_tokens)
            m = 0
            while m < want and j - 1 - m >= 0 and context[j - 1 - m] == context[L - 2 - m]:
                m += 1
            if (m, avail) > best:
                best, best_j = (m, avail), j
                if m >= want and avail >= max_tokens:
                    break  # longest n-gram, full draft, most recent such
        if best[0] + 1 < self.ngram_min:
            return _EMPTY
        return context[best_j + 1 : best_j + 1 + max_tokens].copy()


def count_accepted(row_argmax: np.ndarray, draft: np.ndarray) -> int:
    """Greedy left-to-right verification: the longest prefix of ``draft``
    where the step's per-row argmax agrees. ``row_argmax[j]`` is the model's
    next token given the history plus draft tokens ``0..j-1`` (row 0: given
    the history alone), so agreement at ``j`` means ``draft[j]`` IS what
    plain greedy decode would have emitted — accepted tokens are
    byte-identical to the unspeculated stream by construction."""
    draft = np.asarray(draft, np.int32).reshape(-1)
    k = int(draft.size)
    if k == 0:
        return 0
    disagree = np.nonzero(np.asarray(row_argmax, np.int32)[:k] != draft)[0]
    return int(disagree[0]) if disagree.size else k
